"""Out-of-process pandas UDF workers (GpuArrowEvalPythonExec + BatchQueue
+ PythonWorkerSemaphore roles): Arrow IPC to persistent spawned workers,
pipelined batch streaming, semaphore-bounded leasing, in-process
fallback for unpicklable functions."""
import os
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.exec.python_worker import (PythonWorkerPool,
                                                 PythonWorkerError)


def _double_fn(it):
    for pdf in it:
        pdf["v"] = pdf["v"] * 2
        yield pdf


def _pid_fn(it):
    import os as _os
    for pdf in it:
        pdf["v"] = _os.getpid()
        yield pdf


def _sleepy_fn(it):
    import time as _time
    for pdf in it:
        _time.sleep(0.08)
        yield pdf


def _grouped_sum(pdf):
    return pdf.groupby("k", as_index=False).agg(s=("v", "sum"))


def _tables(n_batches=4, rows=100):
    rng = np.random.default_rng(0)
    for _ in range(n_batches):
        yield pa.table({"k": rng.integers(0, 5, rows),
                        "v": rng.integers(0, 100, rows).astype("int64")})


class TestWorkerPool:
    def test_map_runs_out_of_process(self):
        pool = PythonWorkerPool(1)
        schema = pa.schema([("k", pa.int64()), ("v", pa.int64())])
        outs = list(pool.run_map(_pid_fn, _tables(2), schema))
        pids = {v for t in outs for v in t.column("v").to_pylist()}
        assert pids and os.getpid() not in pids, \
            "UDF must run in a DIFFERENT process"

    def test_map_results_correct(self):
        pool = PythonWorkerPool(1)
        schema = pa.schema([("k", pa.int64()), ("v", pa.int64())])
        ins = list(_tables(3))
        outs = list(pool.run_map(_double_fn, iter(ins), schema))
        got = [v for t in outs for v in t.column("v").to_pylist()]
        want = [v * 2 for t in ins for v in t.column("v").to_pylist()]
        assert got == want

    def test_worker_reuse_across_tasks(self):
        pool = PythonWorkerPool(1)
        schema = pa.schema([("k", pa.int64()), ("v", pa.int64())])
        p1 = {v for t in pool.run_map(_pid_fn, _tables(1), schema)
              for v in t.column("v").to_pylist()}
        p2 = {v for t in pool.run_map(_pid_fn, _tables(1), schema)
              for v in t.column("v").to_pylist()}
        assert p1 == p2, "persistent worker must be reused"

    def test_pipelining_overlaps_producer_and_worker(self):
        """BatchQueue role: the FIRST result must arrive while the
        producer is still emitting later batches — direct evidence of
        producer/worker overlap, robust to machine load (a wall-clock
        bound would flake on a contended box)."""
        pool = PythonWorkerPool(1)
        schema = pa.schema([("k", pa.int64()), ("v", pa.int64())])
        # warm the persistent worker (spawn + pandas import dominate a
        # cold first task); the pool contract is reuse
        list(pool.run_map(_sleepy_fn, _tables(1), schema))

        stamps = {"last_produced": None, "first_result": None}

        def slow_producer():
            for t in _tables(6):
                time.sleep(0.08)
                stamps["last_produced"] = time.perf_counter()
                yield t
        for out in pool.run_map(_sleepy_fn, slow_producer(), schema):
            if stamps["first_result"] is None:
                stamps["first_result"] = time.perf_counter()
        assert stamps["first_result"] is not None
        assert stamps["first_result"] < stamps["last_produced"], \
            "first result must land while the producer is still " \
            "emitting (no overlap observed)"

    def test_semaphore_bounds_concurrent_leases(self):
        pool = PythonWorkerPool(1)
        acquired = pool._sem.acquire(timeout=1)
        assert acquired
        try:
            w = None
            got = pool._sem.acquire(timeout=0.2)
            assert not got, "semaphore must bound leases"
        finally:
            pool._sem.release()

    def test_worker_error_propagates(self):
        pool = PythonWorkerPool(1)
        schema = pa.schema([("v", pa.int64())])
        with pytest.raises(PythonWorkerError):
            list(pool.run_map(_raises_fn, _tables(1), schema))


def _raises_fn(it):
    for pdf in it:
        raise ValueError("boom in udf")


class TestEngineIntegration:
    def test_map_in_pandas_out_of_process(self):
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.python.useWorkerProcesses": True}))
        df = s.create_dataframe({
            "k": np.arange(50, dtype=np.int64),
            "v": np.arange(50, dtype=np.int64)})
        out = df.map_in_pandas(_double_fn, "k long, v long").to_arrow()
        assert out.column("v").to_pylist() == [v * 2 for v in range(50)]

    def test_apply_in_pandas_out_of_process(self):
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.python.useWorkerProcesses": True}))
        rng = np.random.default_rng(1)
        k = rng.integers(0, 4, 200).astype(np.int64)
        v = rng.integers(0, 100, 200).astype(np.int64)
        df = s.create_dataframe({"k": k, "v": v})
        out = df.group_by("k").apply_in_pandas(
            _grouped_sum, "k long, s long").to_arrow()
        got = dict(zip(out.column("k").to_pylist(),
                       out.column("s").to_pylist()))
        import collections
        want = collections.defaultdict(int)
        for kk, vv in zip(k, v):
            want[int(kk)] += int(vv)
        assert got == dict(want)

    def test_unpicklable_fn_falls_back_in_process(self):
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.python.useWorkerProcesses": True}))
        df = s.create_dataframe({"v": np.arange(10, dtype=np.int64)})
        bump = 7

        def closure_fn(it):            # captures `bump`: not picklable
            for pdf in it:
                pdf["v"] = pdf["v"] + bump
                yield pdf
        out = df.map_in_pandas(closure_fn, "v long").to_arrow()
        assert out.column("v").to_pylist() == [v + 7 for v in range(10)]



def _input_error_iter():
    yield from _tables(1)
    raise RuntimeError("upstream exec failed")


class TestInputErrorPropagation:
    def test_input_iterator_error_propagates_no_hang(self):
        """An upstream error while streaming input must propagate, not
        deadlock the worker round trip (the writer always terminates
        the stream)."""
        pool = PythonWorkerPool(1)
        schema = pa.schema([("k", pa.int64()), ("v", pa.int64())])
        with pytest.raises(RuntimeError, match="upstream exec failed"):
            list(pool.run_map(_double_fn, _input_error_iter(), schema))
