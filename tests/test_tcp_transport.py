"""TCP transport tests: the real cross-process shuffle wire.

Reference role: the UCX transport integration tests — here the wire is
TCP (shuffle/tcp.py) under the same SPI, exercised three ways:
1. frame codec round trips (pure host logic),
2. two transports in one process over real sockets (loopback),
3. a TRUE two-OS-process shuffle: a child process holds map output and
   serves it over TCP; the parent fetches and must reconstruct rows
   identical to a local shuffle of the same input.
"""
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle import (
    BlockIdSpec, MapOutputTracker, MetadataRequest, MetadataResponse,
    ShuffleExecutorContext, ShuffleFetchFailedError, TransferRequest,
    TransferResponse, build_table_meta)
from spark_rapids_tpu.shuffle.tcp import (
    TcpTransport, _dec_mdreq, _dec_mdresp, _dec_trreq, _dec_trresp,
    _enc_mdreq, _enc_mdresp, _enc_trreq, _enc_trresp)


def make_batch(n=10, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({
        "a": rng.integers(-100, 100, n).astype(np.int64),
        "b": rng.standard_normal(n),
        "s": [None if i % 7 == 3 else f"w{i}-{seed}" for i in range(n)],
    })


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

class TestFrameCodec:
    def test_metadata_request_roundtrip(self):
        req = MetadataRequest(42, [BlockIdSpec(1, 2, 3),
                                   BlockIdSpec(7, 0, 5)])
        out = _dec_mdreq(memoryview(_enc_mdreq(req)))
        assert out.request_id == 42
        assert out.blocks == req.blocks

    def test_metadata_response_roundtrip(self):
        meta, _ = build_table_meta(make_batch(9, seed=2))
        resp = MetadataResponse(7, [[meta], []])
        out = _dec_mdresp(memoryview(_enc_mdresp(resp)))
        assert out.request_id == 7
        assert out.error is None
        assert len(out.tables) == 2
        assert out.tables[0][0].num_rows == 9
        assert out.tables[0][0].total_bytes == meta.total_bytes
        assert out.tables[1] == []

    def test_metadata_response_error(self):
        resp = MetadataResponse(9, [], error="no such block")
        out = _dec_mdresp(memoryview(_enc_mdresp(resp)))
        assert out.error == "no such block"

    def test_transfer_roundtrip(self):
        req = TransferRequest(3, [(BlockIdSpec(0, 1, 2), 0),
                                  (BlockIdSpec(0, 2, 2), 1)], [100, 101])
        out = _dec_trreq(memoryview(_enc_trreq(req)))
        assert out.tables == req.tables
        assert out.tags == req.tags
        resp = TransferResponse(3, False, error="busy")
        r2 = _dec_trresp(memoryview(_enc_trresp(resp)))
        assert (r2.accepted, r2.error) == (False, "busy")


# ---------------------------------------------------------------------------
# loopback sockets, one process
# ---------------------------------------------------------------------------

@pytest.fixture()
def two_tcp_executors():
    tracker = MapOutputTracker()
    ta = TcpTransport("exec-a")
    tb = TcpTransport("exec-b")
    ta.add_peer("exec-b", tb.address)
    tb.add_peer("exec-a", ta.address)
    ex_a = ShuffleExecutorContext("exec-a", ta, tracker,
                                  bounce_buffer_size=64,
                                  num_bounce_buffers=2)
    ex_b = ShuffleExecutorContext("exec-b", tb, tracker,
                                  bounce_buffer_size=64,
                                  num_bounce_buffers=2)
    yield ex_a, ex_b
    ta.close()
    tb.close()


class TestTcpLoopback:
    def test_remote_fetch(self, two_tcp_executors):
        ex_a, ex_b = two_tcp_executors
        b0 = make_batch(11, seed=5)
        b1 = make_batch(7, seed=6)
        ex_a.write_map_output(0, 0, {0: [b0], 1: [b1]})
        b2 = make_batch(5, seed=7)
        ex_b.write_map_output(0, 1, {0: [b2]})

        out = list(ex_b.read_partition(0, 0, timeout_s=10.0))
        dicts = [o.to_pydict() for o in out]
        assert len(out) == 2
        assert b2.to_pydict() in dicts
        assert b0.to_pydict() in dicts

        # purely-remote partition, multi-window (batch >> 64B bounce)
        out1 = list(ex_b.read_partition(0, 1, timeout_s=10.0))
        assert len(out1) == 1
        assert out1[0].to_pydict() == b1.to_pydict()

    def test_fetch_unreachable_peer_raises(self, two_tcp_executors):
        ex_a, ex_b = two_tcp_executors
        ex_a.write_map_output(0, 0, {0: [make_batch(4, seed=8)]})
        # exec-a's transport dies (executor loss)
        ex_a.transport.close()
        time.sleep(0.05)
        with pytest.raises(ShuffleFetchFailedError):
            list(ex_b.read_partition(0, 0, timeout_s=2.0))


# ---------------------------------------------------------------------------
# two OS processes
# ---------------------------------------------------------------------------

def _child_serve(q_out, q_in):
    """Child executor: builds map output, serves it over TCP."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.shuffle import (MapOutputTracker,
                                          ShuffleExecutorContext)
    from spark_rapids_tpu.shuffle.tcp import TcpTransport

    transport = TcpTransport("exec-child")
    tracker = MapOutputTracker()
    ctx = ShuffleExecutorContext("exec-child", transport, tracker,
                                 bounce_buffer_size=256,
                                 num_bounce_buffers=2)
    # the child's half of the shuffle map side: rows where k % 2 == 1
    rng = np.random.default_rng(123)
    k = rng.integers(0, 10, 500).astype(np.int64)
    v = rng.standard_normal(500)
    mask = (np.arange(500) % 2) == 1
    per_reduce = {}
    for pid in range(4):
        sel = mask & (k % 4 == pid)
        if sel.any():
            per_reduce[pid] = [ColumnarBatch.from_pydict(
                {"k": k[sel], "v": v[sel]})]
    ctx.write_map_output(5, 1, per_reduce)
    q_out.put(("ready", transport.address,
               sorted(per_reduce.keys())))
    # serve until the parent says stop
    q_in.get(timeout=60)
    transport.close()


class TestTcpTwoProcesses:
    def test_cross_process_shuffle_identical_rows(self):
        ctx_mp = mp.get_context("spawn")
        q_out = ctx_mp.Queue()
        q_in = ctx_mp.Queue()
        child = ctx_mp.Process(target=_child_serve, args=(q_out, q_in),
                               daemon=True)
        child.start()
        try:
            msg, child_addr, child_parts = q_out.get(timeout=120)
            assert msg == "ready"

            # parent executor: its own half (k rows at even indices) +
            # remote fetch of the child's half
            transport = TcpTransport("exec-parent")
            transport.add_peer("exec-child", tuple(child_addr))
            tracker = MapOutputTracker()
            ctx = ShuffleExecutorContext("exec-parent", transport, tracker,
                                         bounce_buffer_size=256,
                                         num_bounce_buffers=2)
            rng = np.random.default_rng(123)
            k = rng.integers(0, 10, 500).astype(np.int64)
            v = rng.standard_normal(500)
            mask = (np.arange(500) % 2) == 0
            for pid in range(4):
                sel = mask & (k % 4 == pid)
                if sel.any():
                    ctx.write_map_output(5, 0, {pid: [
                        ColumnarBatch.from_pydict({"k": k[sel],
                                                   "v": v[sel]})]})
            # driver role: register the child's map output
            tracker.register_map_output(5, 1, "exec-child")

            got = {}
            for pid in range(4):
                rows = []
                for b in ctx.read_partition(5, pid, timeout_s=30.0):
                    d = b.to_pydict()
                    rows.extend(zip(d["k"], d["v"]))
                got[pid] = sorted(rows)

            # oracle: the same shuffle computed locally
            want = {pid: [] for pid in range(4)}
            for kk, vv in zip(k, v):
                want[int(kk) % 4].append((int(kk), float(vv)))
            for pid in range(4):
                assert got[pid] == sorted(want[pid]), f"partition {pid}"

            # and a query-shaped check: per-key sums over the shuffled
            # rows match a straight groupby of the full input
            import collections
            agg = collections.defaultdict(float)
            for pid in range(4):
                for kk, vv in got[pid]:
                    agg[kk] += vv
            want_agg = collections.defaultdict(float)
            for kk, vv in zip(k, v):
                want_agg[int(kk)] += float(vv)
            for kk in want_agg:
                assert abs(agg[kk] - want_agg[kk]) < 1e-9
            transport.close()
        finally:
            q_in.put("stop")
            child.join(timeout=10)
            if child.is_alive():
                child.terminate()
