"""Test harness config: run on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): unit tests run
against a local, clusterless backend; distributed logic is tested on
virtual devices (their Mockito-mock-transport pattern) rather than real
hardware.
"""
import os

# Tests exercise the 16-row capacity buckets (cheap compiles on the CPU
# backend, and capacity-edge cases stay reachable with tiny inputs); the
# TPU-production default is larger to keep the per-query program count
# down (see columnar/column.py MIN_CAPACITY).
os.environ.setdefault("SPARK_RAPIDS_TPU_MIN_CAPACITY", "16")

# Force the static plan-invariant verifier on for every plan the suite
# lowers, regardless of per-test conf: every tier-1 query plan doubles
# as a verifier regression fixture (spark.rapids.tpu.sql.planVerify).
os.environ.setdefault("SPARK_RAPIDS_TPU_FORCE_PLAN_VERIFY", "1")

# Force EXACT exchange-stats mode: every map batch sketched, no
# sampling (spark.rapids.tpu.obs.stats.sampleEvery), so stats digests
# and skew/distinct verdicts stay deterministic under test.  Sampling
# behavior itself is tested by setting the conf explicitly with an acc
# built directly (tests/test_obs_overhead.py).
os.environ.setdefault("SPARK_RAPIDS_TPU_OBS_STATS_EXACT", "1")

# Force the residency transfer guard on for every query the suite
# drains: undeclared device->host pulls raise UndeclaredTransferError
# instead of silently stalling the pipeline.  Declared sites
# (analysis/residency.py SITES) lift the guard for their scoped pull.
# Export SPARK_RAPIDS_TPU_FORCE_TRANSFER_GUARD=0 to switch off when
# bisecting (spark.rapids.tpu.analysis.residency.transferGuard).
os.environ.setdefault("SPARK_RAPIDS_TPU_FORCE_TRANSFER_GUARD", "1")

# The image's sitecustomize registers the axon TPU backend and forces
# JAX_PLATFORMS=axon in every interpreter, so the env var alone is not
# enough — override through the config API after import, before any
# backend is initialized.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# TPU_TEST_PLATFORM=axon runs the suite against the real chip (smoke runs);
# default is the virtual 8-device CPU mesh.
jax.config.update("jax_platforms",
                  os.environ.get("TPU_TEST_PLATFORM", "cpu"))

# No PERSISTENT compile cache under the CPU test mesh: XLA:CPU AOT
# executables re-loaded across processes trip a machine-feature
# mismatch in cpu_aot_loader (flaky SIGILL/segfault mid-suite); CPU
# compiles at the 16-row test sizes are cheap, so cache nothing.
if os.environ.get("TPU_TEST_PLATFORM", "cpu") == "cpu":
    try:
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:
        pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: non-gating perf/soak checks excluded from the tier-1 "
        "run (-m 'not slow')")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# LLVM's JIT code arena fails hard (segfault on the next compile) once
# a single process accumulates enough live XLA:CPU executables; the
# engine's (op, schema, bucket) program caches pin them.  Dropping all
# compile caches every 100 tests keeps the whole suite inside the
# arena; test-size recompiles are cheap.
_TESTS_RUN = {"n": 0}


@pytest.fixture(autouse=True)
def _suite_compile_arena_bound():
    yield
    _TESTS_RUN["n"] += 1
    if _TESTS_RUN["n"] % 100 == 0:
        from spark_rapids_tpu.shims.compile_caches import \
            clear_compile_caches
        clear_compile_caches()
