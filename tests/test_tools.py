"""Tools tests: event logs, qualification, profiling, explain, exports."""
import json
import os

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.tools.events import read_event_log
from spark_rapids_tpu.tools.qualification import qualify
from spark_rapids_tpu.tools.profiling import analyze, generate_dot

from data_gen import IntGen, KeyGen, gen_df


def _run_queries(tmp_path, enabled=True):
    log = str(tmp_path / "events.jsonl")
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": enabled,
        "spark.rapids.tpu.eventLog.path": log,
    }))
    df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 100)
    df.group_by("k").agg(F.sum("v").alias("s")).collect()
    df.filter(F.col("v") > 0).collect()
    return log


class TestEventLog:
    def test_event_log_written(self, tmp_path):
        log = _run_queries(tmp_path)
        records = read_event_log(log)
        assert len(records) == 2
        assert records[0]["wall_ms"] > 0
        assert any("TpuHashAggregate" in n for n in records[0]["nodes"])
        assert records[0]["node_metrics"]

    def test_qualification(self, tmp_path):
        log = _run_queries(tmp_path)
        q = qualify(read_event_log(log))
        assert q["app_score"] >= 0.9
        assert q["recommendation"] == "STRONGLY RECOMMENDED"

    def test_qualification_cpu_run(self, tmp_path):
        log = _run_queries(tmp_path, enabled=False)
        q = qualify(read_event_log(log))
        assert q["app_score"] == 0.0
        assert q["recommendation"] == "NOT RECOMMENDED"

    def test_profiling_analyze_and_dot(self, tmp_path):
        log = _run_queries(tmp_path)
        records = read_event_log(log)
        a = analyze(records)
        assert a["num_queries"] == 2
        assert any(k.startswith("Tpu") for k in a["operator_totals"])
        dot = generate_dot(records[0])
        assert dot.startswith("digraph") and "TpuHashAggregate" in dot


class TestExplainAndExport:
    def test_explain_mentions_tpu_ops(self):
        s = TpuSession(TpuConf({}))
        df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 50)
        text = s.explain(df.group_by("k").agg(F.sum("v").alias("x"))._plan)
        assert "TpuHashAggregate" in text

    def test_explain_shows_fallback(self):
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": False}))
        df = gen_df(s, {"k": KeyGen()}, 10)
        text = s.explain(df._plan)
        assert "CPU fallbacks" in text

    def test_to_device_batches(self):
        s = TpuSession(TpuConf({}))
        df = gen_df(s, {"k": KeyGen(null_ratio=0), "v": IntGen(
            null_ratio=0)}, 64)
        batches = df.to_device_batches()
        assert sum(b.num_rows for b in batches) == 64
        arrs = df.to_jax()
        assert set(arrs) == {"k", "v"}
        assert int(arrs["k"].shape[0]) == 64

    def test_test_mode_asserts_on_fallback(self):
        import pytest
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.test.enabled": True}))
        from spark_rapids_tpu.udf import udf
        from spark_rapids_tpu.columnar import dtypes as T
        df = gen_df(s, {"k": KeyGen()}, 10)
        # window RANGE frame is not TPU-supported -> CPU fallback -> assert
        from spark_rapids_tpu.plan import logical as L
        bad = df.with_window("w", F.sum("k"), partition_by=["k"],
                             frame=("range", None, 0))
        with pytest.raises(AssertionError):
            bad.collect()


def test_profile_trace_dir(tmp_path):
    """spark.rapids.tpu.profile.traceDir captures an xprof trace
    (reference: NVTX ranges + Nsight, SURVEY.md §5)."""
    import os
    from harness import with_tpu_session
    d = str(tmp_path / "trace")

    def run(s):
        s.set_conf("spark.rapids.tpu.profile.traceDir", d)
        df = s.create_dataframe({"a": [1, 2, 3]})
        from spark_rapids_tpu.api import functions as F
        df.agg(F.sum("a").alias("s")).collect()
        return []
    with_tpu_session(run)
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "no trace files captured"
