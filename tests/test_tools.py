"""Tools tests: event logs, qualification, profiling, explain, exports."""
import json
import os

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.tools.events import read_event_log
from spark_rapids_tpu.tools.qualification import qualify
from spark_rapids_tpu.tools.profiling import analyze, generate_dot

from data_gen import IntGen, KeyGen, gen_df


def _run_queries(tmp_path, enabled=True):
    log = str(tmp_path / "events.jsonl")
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": enabled,
        "spark.rapids.tpu.eventLog.path": log,
    }))
    df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 100)
    df.group_by("k").agg(F.sum("v").alias("s")).collect()
    df.filter(F.col("v") > 0).collect()
    return log


class TestEventLog:
    def test_event_log_written(self, tmp_path):
        log = _run_queries(tmp_path)
        records = read_event_log(log)
        assert len(records) == 2
        assert records[0]["wall_ms"] > 0
        assert any("TpuHashAggregate" in n for n in records[0]["nodes"])
        assert records[0]["node_metrics"]

    def test_qualification(self, tmp_path):
        log = _run_queries(tmp_path)
        q = qualify(read_event_log(log))
        assert q["app_score"] >= 0.9
        assert q["recommendation"] == "STRONGLY RECOMMENDED"

    def test_qualification_cpu_run(self, tmp_path):
        log = _run_queries(tmp_path, enabled=False)
        q = qualify(read_event_log(log))
        assert q["app_score"] == 0.0
        assert q["recommendation"] == "NOT RECOMMENDED"

    def test_profiling_analyze_and_dot(self, tmp_path):
        log = _run_queries(tmp_path)
        records = read_event_log(log)
        a = analyze(records)
        assert a["num_queries"] == 2
        assert any(k.startswith("Tpu") for k in a["operator_totals"])
        dot = generate_dot(records[0])
        assert dot.startswith("digraph") and "TpuHashAggregate" in dot


class TestDoctorReportTolerance:
    """``report.py --doctor`` on PRE-r12 event logs: records written
    before the doctor plane existed carry no ``doctor`` block and must
    render a one-line placeholder, not crash (the same convention as
    ``--memory`` on pre-r11 logs)."""

    def test_doctor_lines_placeholder_on_old_record(self):
        from spark_rapids_tpu.tools.report import doctor_lines
        (line,) = doctor_lines({"query_id": "old"})
        assert "no doctor verdict recorded" in line

    def test_report_cli_doctor_on_pre_r12_log(self, tmp_path, capsys):
        from spark_rapids_tpu.tools import report
        log = _run_queries(tmp_path)
        # strip the doctor blocks to reconstruct a pre-r12 log
        stripped = []
        with open(log) as f:
            for line in f:
                rec = json.loads(line)
                rec.pop("doctor", None)
                stripped.append(rec)
        with open(log, "w") as f:
            for rec in stripped:
                f.write(json.dumps(rec) + "\n")
        rc = report.main([log, "--doctor"])
        out = capsys.readouterr().out
        assert rc in (0, None)
        assert "no doctor verdict recorded" in out

    def test_report_cli_doctor_on_current_log(self, tmp_path, capsys):
        from spark_rapids_tpu.tools import report
        log = _run_queries(tmp_path)
        rc = report.main([log, "--doctor"])
        out = capsys.readouterr().out
        assert rc in (0, None)
        assert "query doctor (cross-plane verdict)" in out
        assert "no doctor verdict recorded" not in out


class TestExplainAndExport:
    def test_explain_mentions_tpu_ops(self):
        s = TpuSession(TpuConf({}))
        df = gen_df(s, {"k": KeyGen(), "v": IntGen()}, 50)
        text = s.explain(df.group_by("k").agg(F.sum("v").alias("x"))._plan)
        assert "TpuHashAggregate" in text

    def test_explain_shows_fallback(self):
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": False}))
        df = gen_df(s, {"k": KeyGen()}, 10)
        text = s.explain(df._plan)
        assert "CPU fallbacks" in text

    def test_to_device_batches(self):
        s = TpuSession(TpuConf({}))
        df = gen_df(s, {"k": KeyGen(null_ratio=0), "v": IntGen(
            null_ratio=0)}, 64)
        batches = df.to_device_batches()
        assert sum(b.num_rows for b in batches) == 64
        arrs = df.to_jax()
        assert set(arrs) == {"k", "v"}
        assert int(arrs["k"].shape[0]) == 64

    def test_test_mode_asserts_on_fallback(self):
        import pytest
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.test.enabled": True}))
        from spark_rapids_tpu.udf import udf
        from spark_rapids_tpu.columnar import dtypes as T
        df = gen_df(s, {"k": KeyGen()}, 10)
        # window RANGE frame is not TPU-supported -> CPU fallback -> assert
        from spark_rapids_tpu.plan import logical as L
        bad = df.with_window("w", F.sum("k"), partition_by=["k"],
                             frame=("range", None, 0))
        with pytest.raises(AssertionError):
            bad.collect()


def test_profile_trace_dir(tmp_path):
    """spark.rapids.tpu.profile.traceDir captures an xprof trace
    (reference: NVTX ranges + Nsight, SURVEY.md §5)."""
    import os
    from harness import with_tpu_session
    d = str(tmp_path / "trace")

    def run(s):
        s.set_conf("spark.rapids.tpu.profile.traceDir", d)
        df = s.create_dataframe({"a": [1, 2, 3]})
        from spark_rapids_tpu.api import functions as F
        df.agg(F.sum("a").alias("s")).collect()
        return []
    with_tpu_session(run)
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "no trace files captured"


# ---------------------------------------------------------------------------
# per-parameter TypeSig + cast matrix (TypeChecks.scala:367,879 roles)
# ---------------------------------------------------------------------------

class TestTypeSigDepth:
    def test_per_param_mismatch_tags_fallback(self):
        from spark_rapids_tpu.plan import typesig as TS
        from spark_rapids_tpu.expr import string_ops as es
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.columnar import dtypes as T
        sig = TS.ExprSig(
            [TS.ParamSig("str", TS.STRING_SIG),
             TS.ParamSig("pos", TS.INTEGRAL)], TS.STRING_SIG)
        ok = es.Substring(ec.Literal("abc"), ec.Literal(1), ec.Literal(2))
        # reuse the 'pos' param for the variadic tail
        sig.repeat_last = True
        assert sig.reasons_for(ok) == []
        bad = es.Substring(ec.Literal("abc"), ec.Literal("x"),
                           ec.Literal(2))
        reasons = sig.reasons_for(bad)
        assert any("parameter 'pos'" in r for r in reasons)

    def test_cast_matrix(self):
        from spark_rapids_tpu.plan import typesig as TS
        from spark_rapids_tpu.columnar import dtypes as T
        assert TS.cast_reason(T.INT64, T.FLOAT64) is None
        assert TS.cast_reason(T.STRING, T.DATE) is None
        assert TS.cast_reason(T.DATE, T.BOOL) is not None
        nested = T.ArrayType(T.INT64)
        assert TS.cast_reason(nested, nested) is not None

    def test_unsupported_cast_plans_cpu_fallback(self):
        from spark_rapids_tpu.api import TpuSession, functions as F
        from spark_rapids_tpu.config import TpuConf
        import datetime
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": True}))
        df = s.create_dataframe({
            "d": [datetime.date(2020, 1, 1), datetime.date(2021, 2, 2)]})
        out = df.select(F.col("d").cast("boolean").alias("b"))
        text = s.explain(out._plan)
        assert "Cpu" in text
        assert "not supported on TPU" in text


class TestCboPlacement:
    """Transition-aware subtree placement (CostBasedOptimizer.scala:246)."""

    def test_tiny_plan_stays_on_cpu(self):
        import numpy as np
        from spark_rapids_tpu.api import TpuSession, functions as F
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.sql.optimizer.enabled": True}))
        df = s.create_dataframe({"x": np.arange(4, dtype=np.int64)})
        out = df.filter(F.col("x") > 1)
        text = s.explain(out._plan)
        assert "cost model placed this subtree on CPU" in text or \
            "Cpu" in text
        assert out.collect()          # still correct

    def test_large_plan_stays_on_tpu(self):
        from spark_rapids_tpu.plan import cbo
        from spark_rapids_tpu.plan import logical as L
        import pyarrow as pa
        import numpy as np
        big = pa.table({"x": np.arange(200_000, dtype=np.int64)})
        rel = L.LocalRelation(big, 1)
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.expr import predicates as ep
        f = L.Filter(ep.GreaterThan(ec.AttributeReference("x"),
                                    ec.Literal(5)), rel)
        placement = cbo.choose_placement(f)
        assert placement[id(f)] == "tpu"

    def test_scan_cardinality_from_parquet_footer(self, tmp_path):
        """Scan estimates come from file footers (RowCountPlanVisitor
        reads Spark's file-source statistics the same way)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        from spark_rapids_tpu.plan import cbo
        from spark_rapids_tpu.plan import logical as L
        from spark_rapids_tpu.columnar.schema import Schema
        f = str(tmp_path / "t.parquet")
        pq.write_table(
            pa.table({"a": pa.array(range(1234), type=pa.int64())}), f)
        sc = L.Scan("parquet", [f], Schema.from_ddl("a long"))
        assert cbo.estimate_rows(sc) == 1234.0

    def test_filter_selectivity_by_predicate_shape(self):
        """Equality is more selective than a range compare; AND
        multiplies, OR unions."""
        from spark_rapids_tpu.plan import cbo
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.expr import predicates as ep
        x = ec.AttributeReference("x")
        eq = ep.EqualTo(x, ec.Literal(1))
        gt = ep.GreaterThan(x, ec.Literal(1))
        assert cbo._filter_selectivity(eq) < cbo._filter_selectivity(gt)
        both = cbo._filter_selectivity(ep.And(eq, gt))
        either = cbo._filter_selectivity(ep.Or(eq, gt))
        assert both < cbo._filter_selectivity(eq)
        assert either > cbo._filter_selectivity(gt)

    def test_placement_is_transition_aware(self):
        """A cheap node sandwiched between expensive TPU nodes stays on
        TPU (two extra transitions would cost more than its speedup)."""
        from spark_rapids_tpu.plan import cbo
        from spark_rapids_tpu.plan import logical as L
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.expr import predicates as ep
        import pyarrow as pa
        import numpy as np
        big = pa.table({"x": np.arange(500_000, dtype=np.int64)})
        rel = L.LocalRelation(big, 1)
        inner = L.Filter(ep.GreaterThan(ec.AttributeReference("x"),
                                        ec.Literal(5)), rel)
        proj = L.Project([ec.AttributeReference("x")], inner)
        outer = L.Filter(ep.GreaterThan(ec.AttributeReference("x"),
                                        ec.Literal(7)), proj)
        placement = cbo.choose_placement(outer)
        # the middle projection must NOT flip engines on its own
        sides = {placement[id(n)] for n in (outer, proj, inner)}
        assert sides == {"tpu"}


class TestToolDepth:
    """Round-3 tool depth: speedup estimates, unsupported-op report,
    CSV output, time breakdown (QualificationAppInfo / Analysis roles)."""

    def test_qualification_estimates_and_csv(self, tmp_path):
        log = _run_queries(tmp_path)
        q = qualify(read_event_log(log))
        assert q["estimated_app_speedup"] and \
            q["estimated_app_speedup"] > 1.0
        assert q["unsupported_operators"] == {}
        from spark_rapids_tpu.tools.qualification import to_csv
        csv_text = to_csv(q)
        assert csv_text.splitlines()[0].startswith("query_id,")
        assert len(csv_text.splitlines()) == 1 + len(q["queries"])

    def test_profiling_breakdown(self, tmp_path):
        from spark_rapids_tpu.tools.profiling import breakdown
        log = _run_queries(tmp_path)
        b = breakdown(read_event_log(log))
        assert b["attributed_time_ms"] >= 0
        assert b["time_by_operator_ms"]
        assert abs(sum(b["time_share"].values()) - 1.0) < 0.05


class TestForeignQualification:
    """De-circularized qualification (QualificationMain.scala:29 role):
    score a FOREIGN CPU-Spark trace (operator names + times), not this
    engine's own event logs."""

    def test_foreign_spark_trace_scores(self, tmp_path):
        import json
        from spark_rapids_tpu.tools.qualification import (
            qualify, read_foreign_json, to_csv)
        trace = {"queries": [
            {"query_id": "q1", "duration_ms": 4000.0, "nodes": [
                "WholeStageCodegen (1)", "HashAggregate",
                "Exchange hashpartitioning", "HashAggregate",
                "Project", "Filter", "Scan parquet db.t"]},
            {"query_id": "q2", "duration_ms": 1000.0, "nodes": [
                "SortMergeJoin", "Sort", "Exchange", "MyWeirdUdfExec",
                "Scan parquet x"]},
        ]}
        p = tmp_path / "trace.json"
        p.write_text(json.dumps(trace))
        report = qualify(read_foreign_json(str(p)))
        assert report["total_ms"] == 5000.0
        q1 = report["queries"][0]
        # every q1 operator maps to a TPU exec
        assert q1["tpu_operator_fraction"] == 1.0
        assert q1["recommendation"] == "STRONGLY RECOMMENDED"
        assert q1["estimated_speedup"] > 1.0
        q2 = report["queries"][1]
        assert "MyWeirdUdfExec" in q2["unsupported_ops"]
        assert 0.0 < q2["tpu_operator_fraction"] < 1.0
        assert "MyWeirdUdfExec" in report["unsupported_operators"]
        csv_text = to_csv(report)
        assert "q1" in csv_text and "q2" in csv_text

    def test_native_records_still_score(self):
        from spark_rapids_tpu.tools.qualification import qualify
        report = qualify([
            {"query_id": 0, "wall_ms": 100.0,
             "nodes": ["TpuHashAggregate[k]", "TpuFileScan[parquet]"]}])
        assert report["queries"][0]["tpu_operator_fraction"] == 1.0


class TestApiValidation:
    """ApiValidation.scala role: committed docs must match the live
    registry."""

    def test_committed_docs_match_registry(self):
        import os
        from spark_rapids_tpu.tools.api_validation import audit
        docs = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs")
        problems = audit(docs)
        assert not problems, "\n".join(
            ["docs drift from live registry — regenerate with "
             "python -m spark_rapids_tpu.tools.docgen:"] + problems)

    def test_audit_detects_drift(self, tmp_path):
        import os
        import shutil
        from spark_rapids_tpu.tools.api_validation import audit
        docs = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs")
        bad = tmp_path / "docs"
        bad.mkdir()
        for f in ("supported_ops.md", "configs.md"):
            shutil.copy(os.path.join(docs, f), bad / f)
        text = (bad / "supported_ops.md").read_text()
        (bad / "supported_ops.md").write_text(
            text.replace("CollectList", "CollectEverything", 1))
        assert any("supported_ops" in p for p in audit(str(bad)))


class TestSparkEventLogQualification:
    """Real Spark event-log ingestion (EventsProcessor.scala role): the
    tool parses the history-server JSON-lines format, takes the LAST
    plan per execution (AQE updates replace the original), derives wall
    time from SQLExecutionStart/End, and scores foreign operators."""

    FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "data", "spark_eventlog.jsonl")

    def test_parses_executions_and_walls(self):
        from spark_rapids_tpu.tools.qualification import \
            read_spark_eventlog
        recs = read_spark_eventlog(self.FIXTURE)
        assert len(recs) == 3
        by_id = {r["query_id"]: r for r in recs}
        assert "etl-nightly:sql-0" in by_id
        assert by_id["etl-nightly:sql-0"]["wall_ms"] == 8000.0
        assert by_id["etl-nightly:sql-1"]["wall_ms"] == 4500.0
        nodes0 = by_id["etl-nightly:sql-0"]["nodes"]
        assert "HashAggregate" in nodes0 and "Exchange" in nodes0
        assert "Scan parquet" in nodes0

    def test_aqe_update_replaces_plan(self):
        from spark_rapids_tpu.tools.qualification import \
            read_spark_eventlog
        recs = read_spark_eventlog(self.FIXTURE)
        nodes1 = [r for r in recs
                  if r["query_id"].endswith("sql-1")][0]["nodes"]
        # the AQE final plan (broadcast join) must have replaced the
        # original sort-merge plan
        assert "BroadcastHashJoin" in nodes1
        assert "SortMergeJoin" not in nodes1

    def test_qualify_scores_foreign_plans(self):
        from spark_rapids_tpu.tools.qualification import (
            read_spark_eventlog, qualify)
        report = qualify(read_spark_eventlog(self.FIXTURE))
        per_q = {q["query_id"]: q for q in report["queries"]}
        # the aggregation query maps fully onto TPU execs
        assert per_q["etl-nightly:sql-0"]["tpu_operator_fraction"] == 1.0
        assert per_q["etl-nightly:sql-0"]["recommendation"] == \
            "STRONGLY RECOMMENDED"
        assert per_q["etl-nightly:sql-0"]["estimated_speedup"] > 1.0
        # the stateful-streaming exec has no TPU mapping
        assert "FlatMapGroupsWithState" in \
            per_q["etl-nightly:sql-2"]["unsupported_ops"]
        assert "FlatMapGroupsWithState" in \
            report["unsupported_operators"]

    def test_cli_detects_spark_format(self, capsys):
        from spark_rapids_tpu.tools import qualification as Q
        rc = Q.main([self.FIXTURE])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["total_ms"] == 13500.0
        assert len(out["queries"]) == 3


class TestCboExpressionCosts:
    """Expression-level cost model (GpuExpressionCost role, :296):
    host-fallback expressions erase the device advantage, flipping the
    evaluating node to CPU even at large cardinality."""

    def test_regex_project_flips_to_cpu(self):
        from spark_rapids_tpu.plan import cbo, logical as L
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.expr import string_ops as es
        rng = L.Range(0, 600_000, 1, 1)
        plain = L.Project([ec.AttributeReference("id")], rng)
        assert cbo.choose_placement(plain)[id(plain)] == "tpu"
        rx = es.RegexpExtract(
            ec.AttributeReference("id"), ec.Literal("a(b+)"),
            ec.Literal(1))
        heavy = L.Project([rx], rng)
        # host-round-trip regex taxes the device side per row: CPU wins
        assert cbo.choose_placement(heavy)[id(heavy)] == "cpu"

    def test_join_type_cardinalities(self):
        import pyarrow as pa
        import numpy as np
        from spark_rapids_tpu.plan import cbo, logical as L
        from spark_rapids_tpu.expr import core as ec
        left = L.LocalRelation(
            pa.table({"a": np.arange(1000, dtype=np.int64)}), 1)
        right = L.LocalRelation(
            pa.table({"b": np.arange(100, dtype=np.int64)}), 1)
        a = ec.AttributeReference("a")
        b = ec.AttributeReference("b")
        inner = L.Join(left, right, "inner", [a], [b])
        semi = L.Join(left, right, "semi", [a], [b])
        full = L.Join(left, right, "full", [a], [b])
        cross = L.Join(left, right, "cross", [], [])
        assert cbo.estimate_rows(inner) == 1000.0
        assert cbo.estimate_rows(semi) == 500.0
        assert cbo.estimate_rows(full) == 1100.0
        assert cbo.estimate_rows(cross) == 100_000.0
        # global aggregate collapses to one row
        agg = L.Aggregate([], [], left)
        assert cbo.estimate_rows(agg) == 1.0


class TestEventLogDurability:
    """Rotation + flush-per-record + concurrent writers (the
    WatchedFileHandler discipline in tools/events.py)."""

    def test_flush_per_record_is_default(self, tmp_path):
        from spark_rapids_tpu.tools.events import QueryEventLogger
        log = str(tmp_path / "ev.jsonl")
        logger = QueryEventLogger(log)
        assert logger.flush_each
        logger.log_service_event("admitted", "q1")
        # readable immediately, without close()
        assert len(read_event_log(log, events=None)) == 1
        logger.close()

    def test_size_based_rotation(self, tmp_path):
        from spark_rapids_tpu.tools.events import (QueryEventLogger,
                                                   rotated_paths)
        log = str(tmp_path / "ev.jsonl")
        logger = QueryEventLogger(log, max_bytes=300)
        for i in range(20):
            logger.log_service_event("admitted", f"q{i}", pad="x" * 60)
        logger.close()
        assert logger.rotations > 0
        paths = rotated_paths(log)
        assert len(paths) == logger.rotations + 1
        assert paths[-1] == log
        # every record survives across segments, oldest first
        recs = read_event_log(log, events=None, include_rotated=True)
        assert [r["query_id"] for r in recs] == \
            [f"q{i}" for i in range(20)]
        # non-rotated read sees only the live tail
        assert len(read_event_log(log, events=None)) < 20

    def test_env_conf_precedence(self, tmp_path, monkeypatch):
        from spark_rapids_tpu.tools.events import QueryEventLogger
        monkeypatch.setenv("SPARK_RAPIDS_TPU_EVENT_LOG_MAX_BYTES", "1k")
        logger = QueryEventLogger(str(tmp_path / "e.jsonl"))
        assert logger.max_bytes == 1024
        # explicit arg beats env
        logger2 = QueryEventLogger(str(tmp_path / "e.jsonl"),
                                   max_bytes=77)
        assert logger2.max_bytes == 77
        logger.close()
        logger2.close()

    def test_concurrent_writers_one_path(self, tmp_path):
        """Multiple logger instances on one path (session + service)
        under concurrent writes: every line lands intact, including
        across rotations triggered by either instance."""
        import threading
        from spark_rapids_tpu.tools.events import QueryEventLogger
        log = str(tmp_path / "ev.jsonl")
        loggers = [QueryEventLogger(log, max_bytes=2000)
                   for _ in range(3)]
        n_per = 40
        errs = []

        def writer(idx):
            try:
                for i in range(n_per):
                    loggers[idx].log_service_event(
                        "admitted", f"w{idx}-{i}", pad="y" * 40)
            except Exception as e:   # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(len(loggers))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for lg in loggers:
            lg.close()
        assert not errs
        recs = read_event_log(log, events=None, include_rotated=True)
        ids = [r["query_id"] for r in recs]
        assert len(ids) == len(loggers) * n_per
        assert len(set(ids)) == len(ids)       # no torn/duplicated lines


class TestProfilingMultiAttempt:
    """analyze/breakdown over service logs where one query_id carries
    several engine records (retry attempts)."""

    def _multi_attempt_records(self):
        mk = lambda qid, op_ns: {               # noqa: E731
            "event": "query", "query_id": qid, "wall_ms": op_ns / 1e6,
            "physical_plan": "TpuProject\n  TpuLocalScan",
            "nodes": ["TpuProject", "TpuLocalScan"],
            "fallbacks": [],
            "node_metrics": {
                "0:TpuProject": {"opTime": op_ns, "numOutputRows": 10},
                "1:TpuLocalScan": {"opTime": op_ns // 4},
            },
            "conf": {},
        }
        # q1 ran twice (one retry), q2 once
        return [mk("q1", 8_000_000), mk("q1", 2_000_000),
                mk("q2", 4_000_000)]

    def test_analyze_counts_attempts(self):
        recs = self._multi_attempt_records()
        a = analyze(recs)
        assert a["num_queries"] == 3           # records, i.e. attempts
        assert a["operator_totals"]["TpuProject"]["occurrences"] == 3
        assert a["operator_totals"]["TpuProject"]["opTime"] == 14_000_000
        assert a["slowest_queries"][0]["query_id"] == "q1"

    def test_breakdown_aggregates_attempts(self):
        from spark_rapids_tpu.tools.profiling import breakdown
        recs = self._multi_attempt_records()
        b = breakdown(recs)
        assert b["time_by_operator_ms"]["TpuProject"] == 14.0
        assert b["time_by_operator_ms"]["TpuLocalScan"] == 3.5
        assert abs(sum(b["time_share"].values()) - 1.0) < 0.01
        assert b["counters_by_operator"]["TpuProject"][
            "numOutputRows"] == 30
