"""Performance-plane tests: device-utilization timeline (obs/timeline),
compile telemetry (obs/compile_watch), per-tenant SLO accounting
(obs/slo), the Prometheus exposition grammar over the new families, and
the report tool's utilization/compile/SLO rendering."""
import os
import re
import time

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import compile_watch, slo, timeline
from spark_rapids_tpu.obs.prom import render_text
from spark_rapids_tpu.obs.registry import (TIMELINE_GAP_CAUSES,
                                           get_registry)
from spark_rapids_tpu.service.cancellation import CancelToken, \
    query_context
from spark_rapids_tpu.service.metrics import QueryMetrics

MS = 1_000_000          # ns per ms


@pytest.fixture(autouse=True)
def _plane_reset():
    """Isolate the process-wide planes from other tests (and restore
    the default config afterwards — last-configured service wins)."""
    timeline.reset()
    compile_watch.reset()
    slo.reset()
    yield
    default = TpuConf({})
    timeline.configure(default)
    compile_watch.configure(default)
    slo.configure(default)
    timeline.reset()
    compile_watch.reset()
    slo.reset()


def _shares_total(summary):
    return summary["util_pct"] + sum(summary["gaps"].values())


# ---------------------------------------------------------------------------
# timeline: interval accounting + gap classification
# ---------------------------------------------------------------------------

class TestTimeline:
    def test_busy_ms_is_raw_sum_and_shares_sum_to_100(self):
        marker = timeline.begin_query()
        for dur_ms in (5, 3, 2):
            time.sleep(0.001)
            timeline.note_flush(dur_ms * MS)
        s = timeline.query_summary(marker)
        assert s["busy_ms"] == pytest.approx(10.0, abs=1e-6)
        assert s["intervals"] == 3
        assert _shares_total(s) == pytest.approx(100.0, abs=0.05)
        assert set(s["gaps"]) == set(TIMELINE_GAP_CAUSES)

    def test_overlapping_intervals_cap_util_below_100(self):
        # two 6ms windows overlapping by 3ms inside a 10ms window:
        # busy_ms reports the raw (unmerged) sum, util the merged share
        now = time.perf_counter_ns()
        t0 = now - 10 * MS
        timeline._INTERVALS.extend([(t0, t0 + 6 * MS),
                                    (t0 + 3 * MS, t0 + 9 * MS)])
        s = timeline._summarize(0, t0, now, is_query=True)
        assert s["busy_ms"] == pytest.approx(12.0, abs=1e-6)
        assert s["util_pct"] == pytest.approx(90.0, abs=0.01)
        assert _shares_total(s) == pytest.approx(100.0, abs=0.05)

    def test_gap_blames_inline_compile_then_host_staging(self):
        # 20ms window: [0,5) busy, [5,9) covered by a compile record,
        # the rest unexplained -> host_staging in a QUERY summary
        now = time.perf_counter_ns()
        t0 = now - 20 * MS
        timeline._INTERVALS.append((t0, t0 + 5 * MS))
        compile_watch._RECORDS.append({
            "cache": "ut", "dur_ms": 4.0, "signature": "", "inline": True,
            "query_id": None, "end_ns": t0 + 9 * MS})
        s = timeline._summarize(0, t0, now, is_query=True)
        assert s["gaps"]["inline_compile"] == pytest.approx(20.0, abs=0.1)
        assert s["gaps"]["host_staging"] == pytest.approx(55.0, abs=0.1)
        assert s["gaps"]["idle"] == 0.0
        assert _shares_total(s) == pytest.approx(100.0, abs=0.05)
        # the same remainder is "idle" in a PROCESS summary
        p = timeline._summarize(0, t0, now, is_query=False)
        assert p["gaps"]["host_staging"] == 0.0
        assert p["gaps"]["idle"] == pytest.approx(55.0, abs=0.1)

    def test_process_summary_memoizes_and_feeds_gauges(self):
        timeline.note_flush(2 * MS)
        p1 = timeline.process_summary()
        assert timeline.process_summary() is p1       # memo hit
        assert timeline.process_util_pct() == p1["util_pct"]
        total = (timeline.process_util_pct() +
                 sum(timeline.process_gap_pct(c)
                     for c in TIMELINE_GAP_CAUSES))
        assert total == pytest.approx(100.0, abs=0.05)

    def test_disabled_timeline_records_nothing(self):
        timeline.configure(TpuConf({
            "spark.rapids.tpu.obs.timeline.enabled": False}))
        timeline.note_flush(5 * MS)
        assert not timeline._INTERVALS

    def test_warm_query_busy_agrees_with_flush_sum_within_1pct(self):
        # the acceptance contract: a warm engine query's timeline
        # busy_ms equals the flush observer's summed dispatch durations
        from spark_rapids_tpu.obs import profile
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": True}))
        df = (s.create_dataframe(
                {"k": [i % 7 for i in range(4000)],
                 "v": [float(i) for i in range(4000)]}, num_partitions=2)
              .group_by("k").agg(F.sum("v").alias("sv")))
        df.to_arrow()                                  # warm
        marker = profile.begin_query()
        df.to_arrow()
        tl = s.last_query_timeline
        flushes = profile._DISPATCH.get(profile.SITE_FLUSH, [])
        flush_ms = sum(flushes[marker.get(profile.SITE_FLUSH, 0):]) / 1e6
        assert flush_ms > 0
        assert tl["busy_ms"] == pytest.approx(flush_ms, rel=0.01)
        assert _shares_total(tl) == pytest.approx(100.0, abs=0.05)


# ---------------------------------------------------------------------------
# compile_watch: wrap_miss timing, inline attribution, agreement
# ---------------------------------------------------------------------------

class TestCompileWatch:
    def _snap_hist(self, cache):
        hists = get_registry().snapshot()["tpu_compile_seconds"]
        return hists.get(f"cache={cache}", {"count": 0, "sum": 0.0})

    def test_wrap_miss_times_first_call_only(self):
        before = self._snap_hist("ut_cache")

        def fn(x):
            time.sleep(0.02)
            return x + 1

        wrapped = compile_watch.wrap_miss("ut_cache", fn, "(i64[4],)")
        assert wrapped(1) == 2 and wrapped(2) == 3
        recs = compile_watch.records_since(0)
        assert len(recs) == 1                          # first call only
        rec = recs[0]
        assert rec["cache"] == "ut_cache"
        assert rec["dur_ms"] >= 20
        assert rec["signature"] == "(i64[4],)"
        assert not rec["inline"] and rec["query_id"] is None
        after = self._snap_hist("ut_cache")
        # the histogram observed the SAME duration the record stores
        assert after["count"] - before["count"] == 1
        hist_ms = (after["sum"] - before["sum"]) * 1e3
        assert hist_ms == pytest.approx(rec["dur_ms"], abs=1.0)
        assert compile_watch.total_ns() / 1e6 == pytest.approx(
            rec["dur_ms"], abs=1e-3)
        assert compile_watch.inline_ns() == 0

    def test_inline_compile_attributes_to_the_victim_token(self):
        tok = CancelToken("q-inline")
        wrapped = compile_watch.wrap_miss(
            "ut_inline", lambda: time.sleep(0.01), "sig")
        with query_context(tok):
            wrapped()
        rec = compile_watch.records_since(0)[0]
        assert rec["inline"] and rec["query_id"] == "q-inline"
        assert tok.observed["inline_compile_ms"] == pytest.approx(
            rec["dur_ms"], abs=1e-3)
        assert compile_watch.inline_ns() == compile_watch.total_ns()

    def test_stats_section_ranks_slowest_first(self):
        for i, ms in enumerate((1, 30, 5)):
            compile_watch.note_compile(f"c{i}", ms * MS, f"s{i}")
        sec = compile_watch.stats_section(top_n=2)
        assert sec["compiles"] == 2
        assert [r["cache"] for r in sec["top"]] == ["c1", "c2"]
        assert sec["total_compile_ms"] == pytest.approx(36.0, abs=1e-3)

    def test_record_store_evicts_cheapest(self):
        cap = compile_watch._RECORD_CAP
        for i in range(cap + 10):
            compile_watch.note_compile("bulk", (i + 1) * 1000, None)
        recs = compile_watch.records_since(0)
        assert len(recs) == cap
        # the cheapest entries were evicted, the slowest survived
        assert min(r["dur_ms"] for r in recs) >= 10 / 1e3

    def test_disabled_watch_is_passthrough(self):
        from spark_rapids_tpu.obs import costplane
        compile_watch.configure(TpuConf({
            "spark.rapids.tpu.obs.compile.enabled": False}))
        fn = lambda: 7                                 # noqa: E731
        # the cost plane still needs the first-call choke point, so
        # identity passthrough requires BOTH planes off
        costplane.configure(TpuConf({
            "spark.rapids.tpu.obs.cost.enabled": False}))
        try:
            assert compile_watch.wrap_miss("off", fn) is fn
        finally:
            costplane.configure(TpuConf({}))
        wrapped = compile_watch.wrap_miss("off", fn)
        assert wrapped is not fn and wrapped() == 7
        compile_watch.note_compile("off", 5 * MS)
        assert not compile_watch.records_since(0)


# ---------------------------------------------------------------------------
# slo: per-tenant accounting + exactly-one-cause breach attribution
# ---------------------------------------------------------------------------

def _metrics(tenant, queue=0.0, execute=0.0, outcome="completed",
             error=None, inline=0.0):
    m = QueryMetrics("q1", tenant, 0)
    m.queue_wait_ms = queue
    m.execute_ms = execute
    m.outcome = outcome
    m.error = error
    m.inline_compile_ms = inline
    return m


class TestSlo:
    TARGET = {"spark.rapids.tpu.obs.slo.targetMs": 100}

    def test_each_breach_cause_attributed_exactly_once(self):
        slo.configure(TpuConf(self.TARGET))
        slo.record(_metrics("t", outcome="shed"))
        slo.record(_metrics("t", execute=5.0, outcome="cancelled",
                            error="deadline"))
        slo.record(_metrics("t", queue=10.0, execute=200.0, inline=150.0))
        slo.record(_metrics("t", queue=10.0, execute=200.0, inline=1.0))
        slo.record(_metrics("t", execute=50.0))        # under target
        sec = slo.stats_section()
        t = sec["tenants"]["t"]
        assert t["count"] == 5
        assert t["breaches"] == 4
        assert t["breach_causes"] == {"shed": 1, "deadline": 1,
                                      "inline_compile": 1, "slow_exec": 1}
        assert sum(t["breach_causes"].values()) == t["breaches"]
        # burn is the overshoot of the two late completions (110 each)
        assert t["burn_ms"] == pytest.approx(220.0, abs=1e-3)

    def test_no_target_means_histograms_only(self):
        slo.configure(TpuConf({}))                     # targetMs = 0
        slo.record(_metrics("quiet", execute=10_000.0, outcome="shed"))
        t = slo.stats_section()["tenants"]["quiet"]
        assert t["count"] == 1 and t["breaches"] == 0
        assert t["breach_causes"] == {} and t["burn_ms"] == 0.0

    def test_percentiles_are_ordered_and_phase_split(self):
        slo.configure(TpuConf({}))
        for i in range(100):
            slo.record(_metrics("p", queue=float(i), execute=float(2 * i)))
        t = slo.stats_section()["tenants"]["p"]
        assert 0 < t["p50_ms"] <= t["p95_ms"] <= t["p99_ms"]
        assert t["p50_ms"] == pytest.approx(148.5, abs=3.5)
        assert t["queue_p95_ms"] < t["exec_p95_ms"]

    def test_disabled_slo_records_nothing(self):
        slo.configure(TpuConf({
            "spark.rapids.tpu.obs.slo.enabled": False}))
        slo.record(_metrics("gone", execute=1.0))
        assert "gone" not in slo.stats_section()["tenants"]


# ---------------------------------------------------------------------------
# Prometheus exposition grammar over the populated new families
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
_SAMPLE_RE = re.compile(
    rf"^{_NAME}(?:\{{{_LABEL}(?:,{_LABEL})*\}})? "
    r"(?:[+-]?(?:\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|Inf)|NaN)$")
_HELP_RE = re.compile(rf"^# HELP {_NAME} [^\n]*$")
_TYPE_RE = re.compile(rf"^# TYPE {_NAME} (?:counter|gauge|histogram)$")


class TestPrometheusExposition:
    # a tenant name exercising every label-escape rule in the format
    EVIL = 'te"nant\\with\nnewline'

    def test_metrics_text_lints_with_new_families_populated(self):
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": True}))
        timeline.note_flush(2 * MS)
        compile_watch.note_compile("lint", 3 * MS, "(f64[8],)")
        slo.configure(TpuConf({"spark.rapids.tpu.obs.slo.targetMs": 1}))
        slo.record(_metrics(self.EVIL, execute=50.0))
        from spark_rapids_tpu.service.server import QueryService
        with QueryService(s, num_workers=1) as svc:
            svc.submit(s.range(0, 16)).result(60)
            text = svc.metrics_text()

        for family in ("tpu_compile_seconds_bucket",
                       "tpu_compile_seconds_sum",
                       "tpu_device_busy_seconds_total",
                       "tpu_device_util_pct",
                       "tpu_slo_latency_seconds_bucket",
                       "tpu_slo_breaches_total",
                       "tpu_slo_burn_ms_total"):
            assert family in text, f"missing family {family}"
        for cause in TIMELINE_GAP_CAUSES:
            assert f'tpu_device_idle_pct{{cause="{cause}"}}' in text
        # the adversarial tenant renders escaped, never raw
        assert r'tenant="te\"nant\\with\nnewline"' in text

        # line-by-line grammar lint of the whole exposition
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# HELP"):
                assert _HELP_RE.match(line), line
            elif line.startswith("# TYPE"):
                assert _TYPE_RE.match(line), line
            else:
                assert _SAMPLE_RE.match(line), line

    def test_idle_gauge_children_sum_with_util_to_100(self):
        timeline.note_flush(1 * MS)
        text = render_text()
        got = {}
        for line in text.splitlines():
            m = re.match(r'tpu_device_idle_pct\{cause="([^"]+)"\} (\S+)',
                         line)
            if m:
                got[m.group(1)] = float(m.group(2))
            m = re.match(r"tpu_device_util_pct (\S+)", line)
            if m:
                got["util"] = float(m.group(1))
        assert set(got) == set(TIMELINE_GAP_CAUSES) | {"util"}
        assert sum(got.values()) == pytest.approx(100.0, abs=0.05)


# ---------------------------------------------------------------------------
# report: utilization lane, compile table, SLO header
# ---------------------------------------------------------------------------

class TestReportRendering:
    def test_util_lines_render_sorted_gap_breakdown(self):
        from spark_rapids_tpu.tools.report import util_lines
        rec = {"device_util_pct": 40.0, "device_busy_ms": 12.5,
               "util_gap_breakdown": {"host_staging": 35.0,
                                      "inline_compile": 25.0,
                                      "sem_wait": 0.0}}
        lines = util_lines(rec)
        assert lines[0] == "-- device utilization --"
        assert "40.0%" in lines[1] and "12.5" in lines[1]
        body = "\n".join(lines)
        assert body.index("host_staging") < body.index("inline_compile")
        assert "sem_wait" not in body                  # zero shares hidden
        assert util_lines({}) == []

    def test_compile_lines_render_slowest_first(self):
        from spark_rapids_tpu.tools.report import compile_lines
        rec = {"compiles": [
            {"cache": "fused_project", "dur_ms": 12.0, "inline": True,
             "signature": "(i64[4],)"},
            {"cache": "hash_aggregate", "dur_ms": 90.0, "inline": False,
             "signature": "(f64[8],)"}]}
        lines = compile_lines(rec)
        assert lines[0] == "-- compiles in query window --"
        body = "\n".join(lines)
        assert body.index("hash_aggregate") < body.index("fused_project")
        assert compile_lines({}) == []

    def test_slo_header_groups_terminal_records_by_tenant(self):
        from spark_rapids_tpu.tools.report import slo_header
        stories = {f"q{i}": {"service": [
            {"event": "completed", "tenant": "alpha",
             "queue_wait_ms": 1.0, "execute_ms": float(10 * (i + 1))},
            {"event": "admitted", "tenant": "ignored"}]}
            for i in range(4)}
        stories["qx"] = {"service": [
            {"event": "cancelled", "tenant": "beta",
             "queue_wait_ms": 2.0, "execute_ms": 3.0}]}
        lines = slo_header(stories)
        body = "\n".join(lines)
        assert "per-tenant latency" in lines[0]
        assert "alpha" in body and "beta" in body
        assert "ignored" not in body
        assert slo_header({}) == []

    def test_end_to_end_report_carries_the_new_lanes(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.eventLog.path": log}))
        from spark_rapids_tpu.columnar import dtypes as T
        from spark_rapids_tpu.udf import pandas_udf

        # record a compile from INSIDE the query window so the report's
        # compile lane renders even when the process JIT caches are warm
        def _noting(series):
            compile_watch.note_compile("ut_report", 5 * MS, "(i64[n],)")
            return series
        noting = pandas_udf(_noting, return_type=T.FLOAT64)
        df = (s.create_dataframe(
                {"k": [i % 3 for i in range(512)],
                 "v": [float(i) for i in range(512)]})
              .group_by("k").agg(F.sum("v").alias("sv"))
              .select(F.col("k"), noting(F.col("sv")).alias("sv")))
        df.to_arrow()
        from spark_rapids_tpu.tools.report import main as report_main
        out_html = str(tmp_path / "report.html")
        assert report_main([log, "--html", out_html]) == 0
        html = open(out_html).read()
        assert "device utilization" in html
        assert "inline_compile_ms=" in html
        assert "device_util_pct=" in html
        assert "compiles in query window" in html
        assert os.path.getsize(out_html) > 0
