"""Mesh-mode row-producing distributed join and sort.

Reference: GpuShuffledHashJoinBase.scala:28 + GpuSortExec.scala:219 via
GpuShuffleExchangeExec — here each is ONE shard_map SPMD program over
the virtual 8-device CPU mesh (exec/tpu_mesh_join.py,
exec/tpu_mesh_sort.py): rows hash/range-route over lax.all_to_all and
the local join/sort runs per shard.  Oracle = the CPU engine.
"""
import numpy as np
import pytest

from harness import with_cpu_session, with_tpu_session

MESH_CONF = {"spark.rapids.tpu.shuffle.mode": "mesh"}


def _needs_mesh():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")


def _tables(s, n=3000, m=700):
    rng = np.random.default_rng(21)
    left = s.create_dataframe({
        "k": rng.integers(0, 200, n).astype(np.int64),
        "a": rng.integers(-50, 50, n).astype(np.int64),
        "x": rng.random(n),
    }, num_partitions=4)
    right = s.create_dataframe({
        "rk": rng.integers(0, 250, m).astype(np.int64),
        "b": rng.integers(0, 9, m).astype(np.int64),
    }, num_partitions=2)
    return left, right


def _join_q(s, how):
    left, right = _tables(s)
    return left.join(right, left["k"] == right["rk"], how)


def _norm(rows):
    normed = [tuple("N" if v is None else
                    (round(v, 9) if isinstance(v, float) else v)
                    for v in r) for r in rows]
    return sorted(normed, key=lambda r: tuple(str(v) for v in r))


@pytest.mark.parametrize("how", ["inner", "left", "right", "semi", "anti"])
def test_mesh_join_matches_cpu(how):
    _needs_mesh()
    cpu = _norm(with_cpu_session(lambda s: _join_q(s, how).collect()))
    tpu = _norm(with_tpu_session(lambda s: _join_q(s, how).collect(),
                                 conf=MESH_CONF))
    assert cpu == tpu


def test_mesh_join_planned():
    _needs_mesh()

    def run(s):
        df = _join_q(s, "inner")
        df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuMeshShuffledJoin" in tree, tree
        return []
    with_tpu_session(run, conf=MESH_CONF)


def test_mesh_join_nulls_never_match():
    _needs_mesh()

    def q(s):
        import pyarrow as pa
        left = s.create_dataframe(pa.table({
            "k": pa.array([1, None, 2, None, 3], pa.int64()),
            "v": pa.array([10, 20, 30, 40, 50], pa.int64())}),
            num_partitions=2)
        right = s.create_dataframe(pa.table({
            "rk": pa.array([1, None, 3], pa.int64()),
            "w": pa.array([100, 200, 300], pa.int64())}))
        return left.join(right, left["k"] == right["rk"], "left")
    cpu = _norm(with_cpu_session(lambda s: q(s).collect()))
    tpu = _norm(with_tpu_session(lambda s: q(s).collect(),
                                 conf=MESH_CONF))
    assert cpu == tpu


def test_mesh_sort_matches_cpu():
    _needs_mesh()

    def q(s):
        rng = np.random.default_rng(9)
        df = s.create_dataframe({
            "k": rng.integers(-1000, 1000, 5000).astype(np.int64),
            "x": rng.random(5000),
        }, num_partitions=4)
        from spark_rapids_tpu.api import functions as F
        return df.sort(F.col("k"), F.col("x").desc())
    cpu = with_cpu_session(lambda s: q(s).collect())
    tpu = with_tpu_session(lambda s: q(s).collect(), conf=MESH_CONF)
    assert len(cpu) == len(tpu) == 5000
    # global sort: ORDER matters
    for a, b in zip(cpu, tpu):
        assert a[0] == b[0]
        assert abs(a[1] - b[1]) <= 1e-12


def test_mesh_sort_with_nulls_and_planned():
    _needs_mesh()

    def q(s):
        import pyarrow as pa
        df = s.create_dataframe(pa.table({
            "k": pa.array([5, None, 1, 3, None, 2, 4], pa.int64()),
            "v": pa.array(list(range(7)), pa.int64())}),
            num_partitions=2)
        from spark_rapids_tpu.api import functions as F
        return df.sort(F.col("k"))

    def run(s):
        df = q(s)
        rows = df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuMeshSort" in tree, tree
        return rows
    tpu = with_tpu_session(run, conf=MESH_CONF)
    cpu = with_cpu_session(lambda s: q(s).collect())
    assert [r[0] for r in tpu] == [r[0] for r in cpu]


def _string_key_tables(s, n=2000, m=400):
    rng = np.random.default_rng(33)
    cats = [f"cat_{i:03d}" for i in range(120)]
    sub = [f"c{i}" for i in range(150)]
    left = s.create_dataframe({
        "name": [cats[i] for i in rng.integers(0, 120, n)],
        "v": rng.integers(-100, 100, n).astype(np.int64),
    }, num_partitions=4)
    right = s.create_dataframe({
        "rname": [sub[i] if i < 150 else cats[i - 150]
                  for i in rng.integers(0, 270, m)],
        "w": rng.integers(0, 9, m).astype(np.int64),
    }, num_partitions=2)
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_mesh_join_string_keys(how):
    """String (multi-word) join keys route through the mesh program as
    eagerly-computed canon words; payloads stay fixed-width, so the
    key column is projected AWAY (mesh_join_supported's out_ts rule)."""
    _needs_mesh()

    def q(s):
        left, right = _string_key_tables(s)
        j = left.join(right, left["name"] == right["rname"], how)
        keep = ["v"] if how in ("semi", "anti") else ["v", "w"]
        return j.select(*keep)
    cpu = _norm(with_cpu_session(lambda s: q(s).collect()))
    tpu = _norm(with_tpu_session(lambda s: q(s).collect(),
                                 conf=MESH_CONF))
    assert cpu == tpu


def test_mesh_join_string_keys_planned():
    """With required-column pruning, a string-KEY join whose keys are
    projected away really lands on the mesh exec."""
    _needs_mesh()

    def q(s):
        left, right = _string_key_tables(s)
        return left.join(right, left["name"] == right["rname"],
                         "inner").select("v", "w")

    def explain(s):
        return s.explain(q(s)._plan)
    text = with_tpu_session(explain, conf=MESH_CONF)
    assert "TpuMeshShuffledJoin" in text


def test_mesh_join_supported_accepts_string_keys():
    """mesh_join_supported accepts STRING keys (multi-word canon
    encodings route through the all_to_all); only the OUTPUT columns
    must be fixed-width.  The planner limitation that a logical Join's
    schema always carries its key columns means string-key joins engage
    the mesh exec when the keys are fixed-width too — the exec-level
    string path is covered by test_mesh_join_string_keys."""
    from spark_rapids_tpu.exec.tpu_mesh_join import mesh_join_supported
    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.columnar.schema import Schema
    import pyarrow as pa

    class _P:
        join_type = "inner"
        condition = None

        class _E:
            def __init__(self, dt):
                self._dt = dt

            def dtype(self):
                return self._dt
    from spark_rapids_tpu.columnar import dtypes as T
    p = _P()
    p.left_keys = [_P._E(T.STRING)]
    p.right_keys = [_P._E(T.STRING)]
    p.schema = Schema.from_ddl("v long, w long")
    assert mesh_join_supported(p, 8)
    # string OUTPUT still blocks (payloads must be fixed-width)
    p2 = _P()
    p2.left_keys = [_P._E(T.STRING)]
    p2.right_keys = [_P._E(T.STRING)]
    p2.schema = Schema.from_ddl("v string, w long")
    assert not mesh_join_supported(p2, 8)
