"""Runtime stats plane tests (obs/stats.py, obs/profile.py).

Five surfaces:

1. Sketch accuracy — the on-device HLL-style register sketch estimates
   1e5 distinct keys within 15% (default 512 registers: ~4.6% standard
   error, so 15% is a ~3-sigma bound on a seeded, deterministic hash).
2. Determinism — the StatsProfile's stable digest (shuffle exchanges +
   scans) is identical across pipeline parallelism {1, 4} x superstage
   on/off, and the skew verdict repeats exactly; the verdict's
   semantics are pinned at the unit level.
3. The zero-flush contract — enabling stats changes the per-query
   pending-pool flush count by ZERO (the sketch rides the exchange's
   own finalize flush; rows come from the split offsets it already
   pulled).
4. Attribution — a warm fused query produces superstage entries whose
   member time shares sum to exactly 1.0 and whose attributed device
   time/flush counts are populated; dispatch percentiles and the
   ``tpu_stats_*`` Prometheus families are exported.
5. Surfaces — report.py renders the stats sections (and degrades on
   logs without a StatsProfile); the stats files sit in the
   SYNC001/OBS002 lint scope and lint clean.
"""
import json
import os
import sys

import numpy as np

from harness import with_tpu_session

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import pending
from spark_rapids_tpu.obs import flight, stats
from spark_rapids_tpu.obs.prom import render_text
from spark_rapids_tpu.obs.registry import get_registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _agg_join_df(sess, n=50_000, groups=31):
    df = sess.range(0, n, 1, 4)
    df = df.with_column("k", df["id"] % groups)
    dim = sess.range(0, groups, 1, 1).with_column("v", F.col("id") * 2)
    j = df.join(dim.with_column_renamed("id", "k2"),
                df["k"] == F.col("k2"), "inner")
    return j.group_by("k").agg(F.sum("v").alias("sv"))


def _run_warm(df_fn, sess):
    df = df_fn(sess)
    df.collect()            # warm: compile caches + device residency
    rows = df.collect()
    return rows, sess.last_stats_profile


def _shuffles(prof):
    return [e for e in prof["exchanges"] if e["kind"] == "shuffle"]


# ---------------------------------------------------------------------------
# 1. sketch accuracy
# ---------------------------------------------------------------------------

def test_distinct_estimate_within_15pct():
    n = 100_000

    def q(sess):
        # k == id: 1e5 distinct keys through the partial-agg exchange
        df = sess.range(0, n, 1, 4).with_column("k", F.col("id"))
        df = df.group_by("k").agg(F.count().alias("c"))
        return _run_warm(lambda s: df, sess)

    rows, prof = with_tpu_session(
        q, {"spark.rapids.tpu.sql.enabled": "true"})
    assert len(rows) == n
    shuffles = _shuffles(prof.to_dict())
    assert shuffles, "no shuffle exchange recorded"
    e = shuffles[0]
    assert e["rows"] == n
    est = e["distinct_est"]
    assert est is not None
    assert abs(est - n) / n < 0.15, f"distinct est {est} vs true {n}"
    # integral keys decode back from canonical order words
    assert e["key_min"] == 0
    assert e["key_max"] == n - 1
    assert e["null_count"] == 0


# ---------------------------------------------------------------------------
# 2. determinism
# ---------------------------------------------------------------------------

def test_skew_verdict_unit():
    v = stats._skew_verdict(np.array([1000, 10, 10, 10]), 4.0)
    assert v["max_rows"] == 1000 and v["median_rows"] == 10.0
    assert v["ratio"] == 100.0 and v["skewed"] is True
    even = stats._skew_verdict(np.array([10, 10, 10, 10]), 4.0)
    assert even["ratio"] == 1.0 and even["skewed"] is False
    # all-in-one-partition: infinite ratio renders as None, still skewed
    hot = stats._skew_verdict(np.array([100, 0, 0, 0]), 4.0)
    assert hot["ratio"] is None and hot["skewed"] is True
    single = stats._skew_verdict(np.array([100]), 4.0)
    assert single["skewed"] is False          # 1 partition can't skew
    # pure ndarray arithmetic: same input -> same verdict object
    assert stats._skew_verdict(np.array([1000, 10, 10, 10]), 4.0) == v


def test_digest_stable_across_parallelism_and_superstage():
    results = {}
    for par in (1, 4):
        for stage in (True, False):
            def q(sess):
                return _run_warm(_agg_join_df, sess)
            rows, prof = with_tpu_session(q, {
                "spark.rapids.tpu.sql.enabled": "true",
                "spark.rapids.tpu.exec.pipelineParallelism": par,
                "spark.rapids.tpu.sql.superstage": stage})
            assert prof is not None
            results[(par, stage)] = (prof.stable_digest(),
                                     [e["skew"] for e in
                                      _shuffles(prof.to_dict())])
    digests = {d for d, _s in results.values()}
    assert len(digests) == 1, f"digest varies: {results}"
    skews = [s for _d, s in results.values()]
    assert all(s == skews[0] for s in skews)


# ---------------------------------------------------------------------------
# 3. zero extra flushes
# ---------------------------------------------------------------------------

def test_stats_add_zero_flushes():
    def measure(stats_on):
        def q(sess):
            df = _agg_join_df(sess)
            df.collect()
            f0 = pending.FLUSH_COUNT
            df.collect()
            return pending.FLUSH_COUNT - f0, sess.last_stats_profile
        return with_tpu_session(q, {
            "spark.rapids.tpu.sql.enabled": "true",
            "spark.rapids.tpu.obs.stats.enabled": stats_on})
    f_on, prof_on = measure(True)
    f_off, prof_off = measure(False)
    assert f_on == f_off, \
        f"stats added flushes: on={f_on} off={f_off}"
    assert prof_on is not None and prof_off is None
    # the profile's own flush field agrees with the measured delta
    assert prof_on["flushes"] == f_on


# ---------------------------------------------------------------------------
# 4. attribution + export
# ---------------------------------------------------------------------------

def test_member_shares_and_dispatches():
    def q(sess):
        return _run_warm(_agg_join_df, sess)
    _rows, prof = with_tpu_session(
        q, {"spark.rapids.tpu.sql.enabled": "true",
            "spark.rapids.tpu.sql.superstage": "true"})
    d = prof.to_dict()
    assert d["superstages"], "no superstage entries under carving"
    for s in d["superstages"]:
        shares = s["member_share"]
        assert len(shares) == len(s["members"])
        assert abs(sum(shares.values()) - 1.0) < 1e-9
        assert all(v >= 0.0 for v in shares.values())
        # per-member ms re-weights the stage's attributed device time
        assert abs(sum(s["member_device_ms"].values()) -
                   s["device_ms"]) < 0.01 * max(s["device_ms"], 1.0)
    # the warm drain flushed at least once at its barrier, and the
    # attribution scopes caught it
    total_dev = sum(s["device_ms"] for s in d["superstages"])
    total_fl = sum(s["flushes"] for s in d["superstages"])
    assert total_fl >= 1 and total_dev > 0.0
    # dispatch summary: flush site always present for a warm query
    disp = d["dispatches"]
    assert "flush" in disp and "all" in disp
    for v in disp.values():
        assert v["count"] >= 1 and v["p95_ms"] >= v["p50_ms"] >= 0.0


def test_prometheus_and_flight_export():
    def q(sess):
        return _run_warm(_agg_join_df, sess)
    with_tpu_session(q, {"spark.rapids.tpu.sql.enabled": "true"})
    text = render_text(get_registry())
    for family in ("tpu_stats_flush_seconds",
                   "tpu_stats_dispatch_seconds",
                   "tpu_stats_exchanges_total",
                   "tpu_stats_partition_rows",
                   "tpu_stats_last_distinct_keys",
                   "tpu_stats_last_skew_ratio",
                   "tpu_stats_attributed_device_seconds_total"):
        assert family in text, f"{family} missing from exposition"
    # the flight recorder carries EV_STATS breadcrumbs (flush timings
    # and exchange verdicts) for post-mortem bundles
    kinds = {e["kind"] for e in flight.snapshot()}
    assert flight.EV_STATS in kinds


# ---------------------------------------------------------------------------
# 5. surfaces: report rendering, event log, lint scope
# ---------------------------------------------------------------------------

def test_report_renders_stats_sections(tmp_path):
    log = str(tmp_path / "events.jsonl")

    def q(sess):
        return _run_warm(_agg_join_df, sess)
    with_tpu_session(q, {"spark.rapids.tpu.sql.enabled": "true",
                         "spark.rapids.tpu.eventLog.path": log})
    from spark_rapids_tpu.tools import report
    stories = report.load_query_stories(log)
    txt = report.render_report(stories, show_stats=True)
    assert "exchange data statistics" in txt
    assert "superstage device-time attribution" in txt
    assert "dispatch durations" in txt
    # without --stats the sections stay out
    assert "exchange data statistics" not in report.render_report(stories)
    # the event-log record embeds the profile with a stable schema
    with open(log) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    profs = [r["stats_profile"] for r in recs if r.get("stats_profile")]
    assert profs and profs[-1]["version"] == 1


def test_report_tolerates_old_logs():
    """Logs predating the flushes/stats_profile fields render with
    placeholders and an explicit no-profile notice."""
    from spark_rapids_tpu.tools import report
    old = {"engine": [{"physical_plan": "TpuLocalScan",
                       "node_metrics": {"0:TpuLocalScan": {}}}],
           "service": []}
    txt = report.render_query_report("q-old", old, show_stats=True)
    assert "wall_ms=-" in txt
    assert "no StatsProfile recorded" in txt
    assert "flushes=" not in txt


def test_stats_files_in_lint_scope():
    from spark_rapids_tpu.analysis import lint as AL
    for rel in ("spark_rapids_tpu/obs/stats.py",
                "spark_rapids_tpu/obs/profile.py",
                "spark_rapids_tpu/exec/exchange.py"):
        scopes = AL._scopes_for(rel)
        assert AL.SYNC001 in scopes and AL.OBS002 in scopes, rel
        src = open(os.path.join(REPO_ROOT, rel)).read()
        findings = AL.lint_source(src, rel, scopes=scopes)
        assert not findings, [str(f) for f in findings]
