"""Morsel-parallel drain tests (exec/pipeline.py).

Three surfaces:

1. ``drain_parallel`` unit contract on synthetic iterators — order
   preservation, sink placement, backpressure liveness (byte-budget
   head bypass), error propagation + pool recovery, nesting
   (consumer-assist), cancellation unwind, watchdog ident attribution.
2. Engine determinism — the SAME query under pipeline parallelism
   {1, 4} x prefetch {1, 4} must produce BIT-IDENTICAL output (the
   drain reorders work across threads, never results): the bench-shape
   query hashed over its arrow IPC stream, plus TPC-DS q3/q42 row-list
   equality.
3. The thread-safety satellites the pipeline forced: concurrent
   broadcast probes build once; the scan device cache survives
   concurrent executes; the lint queue-receive rule fires elsewhere
   but allowlists pipeline.py's intentional pool park.
"""
import hashlib
import os
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import tpcds  # noqa: E402

from harness import with_tpu_session  # noqa: E402

from spark_rapids_tpu.analysis import lint as AL
from spark_rapids_tpu.exec import pipeline as P
from spark_rapids_tpu.exec.exchange import TpuBroadcastExchange
from spark_rapids_tpu.exec.tpu_basic import TpuLocalScan
from spark_rapids_tpu.memory.arena import DeviceManager
from spark_rapids_tpu.service.cancellation import CancelToken, query_context
from spark_rapids_tpu.service.errors import QueryCancelledError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pipe_conf(par, depth):
    return {"spark.rapids.tpu.exec.pipelineParallelism": par,
            "spark.rapids.tpu.exec.pipelinePrefetchDepth": depth}


def _wait_until(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------------------
# drain_parallel unit contract (synthetic iterators, no session)
# ---------------------------------------------------------------------------

class TestDrainParallel:
    def test_order_preserved_and_sink_applied(self):
        parts = [iter([(p, i) for i in range(5)]) for p in range(6)]
        out = list(P.drain_parallel(parts, sink=lambda t: t + ("s",),
                                    parallelism=4, prefetch_depth=2,
                                    label="order"))
        assert out == [(p, (p, i, "s"))
                       for p in range(6) for i in range(5)]

    def test_serial_and_pipelined_agree(self):
        def make():
            return [iter(range(p, p + 3)) for p in range(5)]
        serial = list(P.drain_parallel(make(), parallelism=1))
        pipelined = list(P.drain_parallel(make(), parallelism=4,
                                          prefetch_depth=3))
        assert serial == pipelined
        assert serial == [(p, v) for p in range(5)
                          for v in range(p, p + 3)]

    def test_single_partition_stays_serial(self):
        # one partition cannot overlap: the drain must degrade to the
        # plain loop (no pool dispatch, pure generator)
        out = list(P.drain_parallel([iter([1, 2, 3])], parallelism=8))
        assert out == [(0, 1), (0, 2), (0, 3)]

    def test_byte_budget_head_bypass_liveness(self):
        # a 1-byte budget is saturated by ANY buffered item; without
        # the head-partition bypass the drain would deadlock — with it,
        # the head's producer may always stage the one item the
        # consumer needs next
        class _Sized:
            def __init__(self, v):
                self.v = v
                self.nbytes = 1 << 20

        parts = [iter([_Sized((p, i)) for i in range(4)])
                 for p in range(4)]
        out = list(P.drain_parallel(parts, parallelism=4,
                                    prefetch_depth=4, byte_budget=1,
                                    label="budget"))
        assert [(pid, item.v) for pid, item in out] == \
            [(p, (p, i)) for p in range(4) for i in range(4)]

    def test_producer_error_propagates_and_pool_recovers(self):
        def bad():
            yield 1
            raise ValueError("boom")

        parts = [iter(range(3)), bad(), iter(range(3))]
        with pytest.raises(ValueError, match="boom"):
            list(P.drain_parallel(parts, parallelism=3,
                                  prefetch_depth=2, label="err"))
        # a failed drain must not wedge the pool: the next drain works
        out = list(P.drain_parallel([iter([0, 1]), iter([2, 3])],
                                    parallelism=2, label="after-err"))
        assert out == [(0, 0), (0, 1), (1, 2), (1, 3)]
        assert _wait_until(lambda: P.busy_workers() == 0)

    def test_nested_drain_makes_progress(self):
        # a sink that itself drains (collect pull -> shuffle
        # materialization nesting): consumer-assist keeps the inner
        # drain live even when every pool worker is busy outside
        def sink(x):
            inner = [iter([x * 10]), iter([x * 10 + 1])]
            return [v for _pid, v in P.drain_parallel(
                inner, parallelism=2, prefetch_depth=1, label="inner")]

        parts = [iter([1, 2]), iter([3]), iter([4, 5])]
        out = list(P.drain_parallel(parts, sink=sink, parallelism=3,
                                    prefetch_depth=2, label="outer"))
        assert out == [(0, [10, 11]), (0, [20, 21]), (1, [30, 31]),
                       (2, [40, 41]), (2, [50, 51])]

    def test_workers_hand_back_partitions_when_permits_pinned(self):
        # regression (REVIEW r06 high), distilled to its deterministic
        # core: a nested drain's consumer holds its device permit
        # re-entrantly (the outer pull region) while every OTHER permit
        # is pinned elsewhere.  Idle pool workers that claim this
        # drain's partitions can never acquire a permit; pre-fix they
        # parked forever in acquire_if_necessary with the partitions
        # stuck _RUNNING, so the consumer — which only assists
        # _UNSTARTED partitions — waited forever too.  Post-fix the
        # workers hand the partitions back within _SEM_TRY_S and the
        # consumer produces them inline on its re-entrant permit.
        sem = DeviceManager.get().semaphore
        permits = sem.permits
        # grow the pool so idle workers exist to claim partitions
        warm = [iter([i]) for i in range(permits + 4)]
        assert len(list(P.drain_parallel(
            warm, parallelism=permits + 4, label="warm"))) == permits + 4

        release = threading.Event()
        pinned = []

        def pin():
            sem.acquire_if_necessary()
            pinned.append(1)
            release.wait(60)
            sem.release_all()

        def part0():
            # keep the consumer busy on pid 0 long past _SEM_TRY_S so
            # pool workers have claimed pids 1..3 (and handed them
            # back) before the consumer reaches them
            time.sleep(0.4)
            yield 0

        out, errs = [], []

        def consume():
            sem.acquire_if_necessary()    # the outer pull region
            try:
                parts = [part0()] + [iter([p]) for p in range(1, 4)]
                out.extend(P.drain_parallel(
                    parts, parallelism=4, prefetch_depth=1,
                    label="pinned"))
            except BaseException as e:  # pragma: no cover - diagnostic
                errs.append(e)
            finally:
                sem.release_all()

        pinners = [threading.Thread(target=pin, daemon=True)
                   for _ in range(permits - 1)]
        t = threading.Thread(target=consume, daemon=True)
        try:
            for p in pinners:
                p.start()
            assert _wait_until(lambda: len(pinned) == permits - 1)
            t.start()
            t.join(60)
            alive = t.is_alive()
        finally:
            release.set()
        assert not alive, "drain deadlocked behind pinned permits"
        assert not errs
        assert out == [(p, p) for p in range(4)]
        for p in pinners:
            p.join(30)
        assert _wait_until(lambda: sem.available() == sem.permits)

    def test_item_nbytes_counts_containers(self):
        # regression (REVIEW r06): the shuffle sink yields nested
        # containers; list/dict contents must count toward the budget
        class _Sized:
            nbytes = 100
        s = _Sized()
        assert P._item_nbytes(s) == 100
        assert P._item_nbytes((s, [s, s])) == 300
        assert P._item_nbytes([s, {"k": s}]) == 200
        assert P._item_nbytes("unsized") == 0

    def test_cancellation_unwinds_workers_and_semaphore(self):
        sem = DeviceManager.get().semaphore
        token = CancelToken(query_id="pipe-cancel")

        def slow(pid):
            for i in range(50):
                time.sleep(0.02)
                yield (pid, i)

        parts = [slow(p) for p in range(4)]
        got = []
        with query_context(token):
            with pytest.raises(QueryCancelledError):
                for out in P.drain_parallel(parts, parallelism=4,
                                            prefetch_depth=1,
                                            token=token, label="cancel"):
                    got.append(out)
                    if len(got) == 2:
                        token.cancel("test-cancel")
        # workers unwind (deregister) and every permit they held — or
        # were waiting on — is returned to the device semaphore
        assert _wait_until(lambda: P.busy_workers() == 0)
        assert _wait_until(lambda: sem.available() == sem.permits)

    def test_worker_idents_attributed_to_query(self):
        # the stall watchdog folds pipeline-worker progress into the
        # owning query via worker_idents(query_id): during a drain the
        # serving pool workers must be registered under the token's id
        token = CancelToken(query_id="pipe-wid")
        started = threading.Event()
        release = threading.Event()

        def part(pid):
            started.set()
            release.wait(30)
            yield pid

        parts = [part(p) for p in range(4)]
        results, errs = [], []

        def consume():
            try:
                with query_context(token):
                    for out in P.drain_parallel(parts, parallelism=4,
                                                prefetch_depth=1,
                                                token=token,
                                                label="wid"):
                        results.append(out)
            except BaseException as e:  # pragma: no cover - diagnostic
                errs.append(e)

        t = threading.Thread(target=consume)
        t.start()
        try:
            assert started.wait(15)
            # registration happens at pool-worker entry (before any
            # semaphore wait), so at least the non-consumer claimers
            # show up under the query id while the drain is in flight
            assert _wait_until(
                lambda: len(P.worker_idents("pipe-wid")) >= 1)
        finally:
            release.set()
        t.join(30)
        assert not errs
        assert results == [(p, p) for p in range(4)]
        # ...and the registration is scoped to the drain
        assert _wait_until(lambda: P.worker_idents("pipe-wid") == [])

    def test_resolve_parallelism_conf(self):
        from spark_rapids_tpu.config import TpuConf
        assert P.resolve_parallelism(TpuConf(
            {"spark.rapids.tpu.exec.pipeline.enabled": False})) == 1
        assert P.resolve_parallelism(TpuConf(
            {"spark.rapids.tpu.exec.pipelineParallelism": 7})) == 7
        # 0 = auto: min(4, cpu count)
        assert 1 <= P.resolve_parallelism(TpuConf({})) <= 4

    def test_pool_stats_shape(self):
        stats = P.pool_stats()
        for key in ("threads", "queued", "busy", "live_drains",
                    "buffered_items", "buffered_bytes"):
            assert key in stats


# ---------------------------------------------------------------------------
# determinism: bit-identical output across parallelism settings
# ---------------------------------------------------------------------------

def _bench_shape_df(s, n_rows=60_000, parts=4):
    """The bench.py query shape (filter -> project -> agg -> join) at
    test scale."""
    from spark_rapids_tpu.api import functions as F
    rng = np.random.default_rng(7)
    df = s.create_dataframe({
        "k": rng.integers(0, 1000, n_rows).astype(np.int64),
        "a": rng.integers(-100_000, 100_000, n_rows).astype(np.int64),
        "x": rng.random(n_rows),
        "y": rng.random(n_rows),
    }, num_partitions=parts)
    dim = s.create_dataframe({
        "dk": np.arange(1000, dtype=np.int64),
        "w": rng.random(1000),
    }, num_partitions=1)
    agg = (df.filter((F.col("x") > 0.1) & (F.col("a") % 7 != 0))
             .with_column("z", F.col("x") * F.col("y") + F.col("a"))
             .group_by("k")
             .agg(F.sum("z").alias("sz"), F.count().alias("c"),
                  F.max("x").alias("mx")))
    return (agg.join(dim, agg["k"] == dim["dk"], "inner")
               .select(F.col("k"), F.col("sz"), F.col("c"),
                       (F.col("mx") * F.col("w")).alias("mw")))


def _ipc_hash(table: pa.Table) -> str:
    table = table.combine_chunks()
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return hashlib.sha256(sink.getvalue().to_pybytes()).hexdigest()


def test_bench_shape_bit_identical_across_parallelism():
    hashes = {}
    for par in (1, 4):
        for depth in (1, 4):
            tbl = with_tpu_session(
                lambda s: _bench_shape_df(s).to_arrow(),
                _pipe_conf(par, depth))
            hashes[(par, depth)] = _ipc_hash(tbl)
    assert len(set(hashes.values())) == 1, hashes


def test_pipeline_disabled_bit_identical():
    on = with_tpu_session(lambda s: _bench_shape_df(s).to_arrow(),
                          _pipe_conf(4, 4))
    off = with_tpu_session(
        lambda s: _bench_shape_df(s).to_arrow(),
        {"spark.rapids.tpu.exec.pipeline.enabled": False})
    assert _ipc_hash(on) == _ipc_hash(off)


@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcds_pipe") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


@pytest.mark.parametrize("query", ["q3", "q42"])
def test_tpcds_identical_across_parallelism(tpcds_dir, query):
    def run(conf):
        def fn(s):
            tpcds.register(s, tpcds_dir)
            return s.sql(tpcds.QUERIES[query]).collect()
        return with_tpu_session(fn, conf)

    serial_rows = run(_pipe_conf(1, 1))
    parallel_rows = run(_pipe_conf(4, 4))
    # exact row-for-row equality INCLUDING order: the pipelined drain
    # must not even reorder rows relative to the serial drain
    assert serial_rows == parallel_rows


# ---------------------------------------------------------------------------
# thread-safety satellites: broadcast build, scan device cache
# ---------------------------------------------------------------------------

def test_broadcast_builds_once_under_concurrent_probes():
    tbl = pa.table({"a": pa.array(range(64), pa.int64()),
                    "b": pa.array([float(i) for i in range(64)],
                                  pa.float64())})
    scan = TpuLocalScan(tbl, num_partitions=4)
    calls = []
    orig_execute = scan.execute
    scan.execute = lambda: (calls.append(1), orig_execute())[1]
    bx = TpuBroadcastExchange(scan)

    barrier = threading.Barrier(2)
    out = [None, None]
    errs = []

    def probe(i):
        try:
            barrier.wait(10)
            out[i] = bx.broadcast_batch()
        except BaseException as e:  # pragma: no cover - diagnostic
            errs.append(e)

    ts = [threading.Thread(target=probe, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errs
    # the double-checked lock: one build, both probes share the result
    assert len(calls) == 1
    assert out[0] is not None and out[0] is out[1]
    assert out[0].num_rows == 64


def test_semaphore_released_restores_reentrant_depth():
    from spark_rapids_tpu.memory.arena import DeviceSemaphore
    sem = DeviceSemaphore(1)
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()          # depth 2, one real permit
    with sem.released():
        assert sem.held_count() == 0
        assert sem.available() == 1     # the permit is actually free
    assert sem.held_count() == 2
    assert sem.available() == 0
    sem.release_all()
    assert sem.available() == 1
    # a thread holding nothing passes through untouched
    with sem.released():
        assert sem.held_count() == 0
    assert sem.held_count() == 0
    assert sem.available() == 1


def test_broadcast_loser_releases_device_permit_while_blocked():
    # regression (REVIEW r06 medium): a probe that reaches the
    # broadcast barrier from a permit-held pull region must not pin the
    # permit while parked behind the winner's build — the permit goes
    # back to the semaphore for the duration and is reacquired after
    sem = DeviceManager.get().semaphore
    gate = threading.Event()
    entered = threading.Event()
    loser_acquired = threading.Event()

    class _GatedScan(TpuLocalScan):
        def execute(self):
            entered.set()
            gate.wait(30)
            return super().execute()

    tbl = pa.table({"a": pa.array(range(8), pa.int64())})
    bx = TpuBroadcastExchange(_GatedScan(tbl, num_partitions=1))
    out = [None, None]
    errs = []

    def winner():
        try:
            out[0] = bx.broadcast_batch()
        except BaseException as e:  # pragma: no cover - diagnostic
            errs.append(e)

    def loser():
        try:
            sem.acquire_if_necessary()      # simulate the pull region
            loser_acquired.set()
            try:
                out[1] = bx.broadcast_batch()
                # permit depth restored once the barrier is crossed
                assert sem.held_count() == 1
            finally:
                sem.release_all()
        except BaseException as e:  # pragma: no cover - diagnostic
            errs.append(e)

    tw = threading.Thread(target=winner)
    tw.start()
    try:
        # the winner owns the build lock, parked inside it on the gate
        assert entered.wait(30)
        tl = threading.Thread(target=loser)
        tl.start()
        assert loser_acquired.wait(30)
        # the loser's permit must return to the semaphore while it
        # parks on the barrier (pre-fix this stayed pinned: permits-1)
        assert _wait_until(lambda: sem.available() == sem.permits)
    finally:
        gate.set()
    tw.join(60)
    tl.join(60)
    assert not errs
    assert out[0] is out[1] and out[0].num_rows == 8
    assert sem.available() == sem.permits


def test_scan_device_cache_single_build_under_concurrent_miss(monkeypatch):
    # regression (REVIEW r06): concurrent misses on the same table must
    # not each upload the full partition set (transient double HBM
    # residency) — the in-progress sentinel makes late arrivals wait
    # for the first builder and share its published parts
    import spark_rapids_tpu.exec.tpu_basic as TB
    tbl = pa.table({"a": pa.array(range(256), pa.int64())})
    builders = []
    orig = TB.from_arrow

    def slow_from_arrow(t):
        builders.append(threading.get_ident())
        time.sleep(0.05)
        return orig(t)

    monkeypatch.setattr(TB, "from_arrow", slow_from_arrow)
    barrier = threading.Barrier(4)
    outs, errs = [], []

    def run():
        try:
            barrier.wait(10)
            outs.append(TB.TpuLocalScan(tbl, num_partitions=2)
                        ._cached_batches())
        except BaseException as e:  # pragma: no cover - diagnostic
            errs.append(e)

    ts = [threading.Thread(target=run) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errs
    assert len(outs) == 4
    # exactly one thread uploaded; everyone shares the same parts
    assert len(set(builders)) == 1
    assert all(o is outs[0] for o in outs)


def test_scan_device_cache_concurrent_executes():
    tbl = pa.table({"a": pa.array(range(1000), pa.int64())})
    totals, errs = [], []

    def run():
        try:
            scan = TpuLocalScan(tbl, num_partitions=2)
            n = 0
            for part in scan.execute():
                for b in part:
                    n += b.num_rows
            totals.append(n)
        except BaseException as e:  # pragma: no cover - diagnostic
            errs.append(e)

    ts = [threading.Thread(target=run) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errs
    assert totals == [1000] * 4


# ---------------------------------------------------------------------------
# lint: the queue-receive rule and pipeline.py's allowlisted park
# ---------------------------------------------------------------------------

_QUEUE_GET_SRC = ("import threading, queue\n"
                  "_lock = threading.Lock()\n"
                  "_tasks = queue.SimpleQueue()\n"
                  "def f():\n"
                  "    with _lock:\n"
                  "        return _tasks.get()\n")


class TestPipelineLint:
    def test_queue_get_under_lock_flagged(self):
        fs = AL.lint_source(_QUEUE_GET_SRC, "service/worker.py",
                            scopes={AL.LOCK001})
        assert any(f.rule == AL.LOCK001 and "queue receive" in f.message
                   for f in fs)

    def test_queue_get_without_lock_clean(self):
        src = ("import queue\n"
               "_tasks = queue.SimpleQueue()\n"
               "def f():\n"
               "    return _tasks.get()\n")
        assert AL.lint_source(src, "service/worker.py",
                              scopes={AL.LOCK001}) == []

    def test_pipeline_pool_park_allowlisted(self):
        fs = AL.lint_source(_QUEUE_GET_SRC,
                            "spark_rapids_tpu/exec/pipeline.py",
                            scopes={AL.LOCK001})
        assert fs == []

    def test_pipeline_module_clean_under_project_scopes(self):
        rel = "spark_rapids_tpu/exec/pipeline.py"
        with open(os.path.join(REPO_ROOT, rel)) as f:
            src = f.read()
        assert AL.lint_source(src, rel,
                              scopes=AL._scopes_for(rel)) == []
