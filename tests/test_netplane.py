"""Shuffle-transport observability plane tests (obs/netplane.py): the
bounded per-edge transfer matrix, the four-phase host-drop tax split,
cross-boundary (query_id, span_id) trace correlation over both
transports, the Prometheus/stats/report/event-log surfaces, and the
satellite instruments (compression byte counters, heartbeat peer
liveness, the client.close() pending-fetch regression)."""
import os
import struct
import time

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import netplane, trace
from spark_rapids_tpu.obs.prom import render_text
from spark_rapids_tpu.obs.registry import get_registry

MS = 1_000_000          # ns per ms


@pytest.fixture(autouse=True)
def _netplane_reset():
    """Isolate the process-wide plane from other tests and restore the
    default config afterwards (last-configured service wins)."""
    netplane.reset()
    yield
    netplane.configure(TpuConf({}))
    netplane.reset()
    trace.disable()
    trace.reset()


# ---------------------------------------------------------------------------
# transfer matrix
# ---------------------------------------------------------------------------

class TestTransferMatrix:
    def test_edges_accumulate_rows_bytes_batches(self):
        netplane.note_serialize(1, 0, 0, rows=10, nbytes=100, dur_ns=MS)
        netplane.note_serialize(1, 0, 0, rows=5, nbytes=50, dur_ns=MS)
        netplane.note_serialize(1, 1, 0, rows=7, nbytes=700, dur_ns=MS)
        m = {(e["shuffle_id"], e["map_id"], e["reduce_id"]): e
             for e in netplane.edge_matrix()}
        assert m[(1, 0, 0)]["rows"] == 15
        assert m[(1, 0, 0)]["bytes"] == 150
        assert m[(1, 0, 0)]["batches"] == 2
        assert m[(1, 1, 0)]["batches"] == 1
        assert netplane.edges_tracked() == 2
        # biggest-bytes-first ordering
        assert netplane.edge_matrix()[0]["bytes"] == 700

    def test_matrix_bound_evicts_not_grows(self):
        netplane.configure(TpuConf({
            "spark.rapids.tpu.obs.net.maxEdges": 2}))
        for rid in range(4):
            netplane.note_serialize(9, 0, rid, rows=1, nbytes=1, dur_ns=1)
        assert netplane.edges_tracked() == 2
        assert netplane.stats_section()["edges_evicted"] == 2

    def test_disabled_plane_records_nothing(self):
        netplane.configure(TpuConf({
            "spark.rapids.tpu.obs.net.enabled": False}))
        assert not netplane.is_enabled()
        netplane.note_serialize(1, 0, 0, rows=1, nbytes=1, dur_ns=MS)
        netplane.note_wire(1, MS)
        netplane.note_deserialize(1, 0, 0, nbytes=1, dur_ns=MS)
        netplane.note_conn("dial")
        assert netplane.edges_tracked() == 0
        s = netplane.query_summary(None)
        assert s["host_drop_tax_ms"] == 0.0 and s["blocks"] == 0

    def test_edge_skew_flags_hot_reduce_partition(self):
        for rid in range(4):
            netplane.note_serialize(3, 0, rid, rows=1, nbytes=100, dur_ns=1)
        assert netplane.query_summary(None)["edge_skew"] == \
            pytest.approx(1.0)
        netplane.note_serialize(3, 1, 0, rows=1, nbytes=900, dur_ns=1)
        # partition 0 holds 1000 of 1300 bytes: max/mean = 1000/325
        assert netplane.query_summary(None)["edge_skew"] == \
            pytest.approx(1000 / 325, abs=0.01)


# ---------------------------------------------------------------------------
# host-drop tax accounting
# ---------------------------------------------------------------------------

class TestHostDropTax:
    def test_phases_sum_to_exchange_wall(self):
        marker = netplane.begin_query()
        netplane.note_serialize(5, 0, 0, rows=4, nbytes=400, dur_ns=2 * MS)
        time.sleep(0.02)                      # host dwell
        netplane.note_wire(400, MS)
        netplane.note_deserialize(5, 0, 0, nbytes=400, dur_ns=MS)
        s = netplane.query_summary(marker)
        ph = s["phases_ms"]
        assert ph["serialize"] == pytest.approx(2.0, abs=1e-6)
        assert ph["wire"] == pytest.approx(1.0, abs=1e-6)
        assert ph["deserialize"] == pytest.approx(1.0, abs=1e-6)
        assert ph["dwell"] > 10.0             # the sleep shows up as dwell
        # the acceptance contract: four phases sum to the wall within 1%
        assert sum(ph.values()) == pytest.approx(
            s["exchange_wall_ms"], rel=0.01, abs=0.02)
        # the tax is the ACTIVE portion only
        assert s["host_drop_tax_ms"] == pytest.approx(4.0, abs=1e-6)
        assert s["staged_bytes"] == 400 and s["wire_bytes"] == 400
        assert s["wire_MBps"] == pytest.approx(0.4, rel=0.01)

    def test_reread_block_cannot_exceed_wall(self):
        marker = netplane.begin_query()
        netplane.note_serialize(6, 0, 0, rows=1, nbytes=10, dur_ns=MS)
        netplane.note_deserialize(6, 0, 0, nbytes=10, dur_ns=MS)
        netplane.note_deserialize(6, 0, 0, nbytes=10, dur_ns=MS)  # retry
        s = netplane.query_summary(marker)
        assert s["exchange_wall_ms"] >= s["host_drop_tax_ms"]
        assert s["phases_ms"]["dwell"] >= 0.0
        assert sum(s["phases_ms"].values()) == pytest.approx(
            s["exchange_wall_ms"], rel=0.01, abs=0.02)

    def test_query_marker_isolates_window(self):
        netplane.note_serialize(7, 0, 0, rows=1, nbytes=111, dur_ns=MS)
        marker = netplane.begin_query()
        netplane.note_serialize(7, 1, 1, rows=2, nbytes=222, dur_ns=MS)
        s = netplane.query_summary(marker)
        assert s["blocks"] == 1 and s["staged_bytes"] == 222
        assert s["phases_ms"]["serialize"] == pytest.approx(1.0, abs=1e-6)
        edges = netplane.query_edges(marker)
        assert [(e["map_id"], e["reduce_id"]) for e in edges] == [(1, 1)]

    def test_active_windows_blame_shuffle_host_timeline_gap(self):
        # a 20ms idle window where the only evidence is netplane
        # serialize work -> the timeline classifies it shuffle_host
        from spark_rapids_tpu.obs import timeline
        timeline.reset()
        try:
            netplane.note_serialize(8, 0, 0, rows=1, nbytes=1,
                                    dur_ns=15 * MS)
            now = time.perf_counter_ns()
            t0 = now - 20 * MS
            s = timeline._summarize(0, t0, now, is_query=True)
            assert s["gaps"]["shuffle_host"] == pytest.approx(75.0, abs=5.0)
            assert netplane.active_segments(t0, now)
        finally:
            timeline.reset()


# ---------------------------------------------------------------------------
# cross-boundary trace correlation
# ---------------------------------------------------------------------------

class TestTraceContext:
    def test_tcp_frames_carry_trace_context(self):
        from spark_rapids_tpu.shuffle import (BlockIdSpec, MetadataRequest,
                                              TransferRequest)
        from spark_rapids_tpu.shuffle.tcp import (_dec_mdreq, _dec_trreq,
                                                  _enc_mdreq, _enc_trreq)
        req = MetadataRequest(3, [BlockIdSpec(1, 2, 3)],
                              query_id="q-42", span_id=77)
        out = _dec_mdreq(memoryview(_enc_mdreq(req)))
        assert (out.query_id, out.span_id) == ("q-42", 77)
        assert out.blocks == req.blocks
        treq = TransferRequest(4, [(BlockIdSpec(1, 2, 3), 0)], [9],
                               query_id="q-42", span_id=77)
        tout = _dec_trreq(memoryview(_enc_trreq(treq)))
        assert (tout.query_id, tout.span_id) == ("q-42", 77)

    def test_legacy_frame_without_trailer_decodes(self):
        # a frame from a pre-netplane peer stops at the block list: the
        # decoder must tolerate the missing trailer (wire back-compat)
        from spark_rapids_tpu.shuffle.tcp import _BLOCK, _dec_mdreq
        body = struct.pack("<QI", 11, 1) + _BLOCK.pack(1, 2, 3)
        out = _dec_mdreq(memoryview(body))
        assert out.request_id == 11
        assert out.query_id is None and out.span_id == 0

    def test_client_and_server_spans_join_on_span_id(self, tmp_path):
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.shuffle import (EndpointRegistry,
                                              InProcessTransport,
                                              MapOutputTracker,
                                              ShuffleExecutorContext)
        reg = EndpointRegistry.reset()
        trace.enable()
        try:
            tracker = MapOutputTracker()
            ex_a = ShuffleExecutorContext(
                "exec-a", InProcessTransport("exec-a", reg), tracker,
                bounce_buffer_size=64, num_bounce_buffers=2)
            ex_b = ShuffleExecutorContext(
                "exec-b", InProcessTransport("exec-b", reg), tracker,
                bounce_buffer_size=64, num_bounce_buffers=2)
            ex_a.write_map_output(0, 0, {0: [ColumnarBatch.from_pydict(
                {"k": list(range(8))})]})
            out = list(ex_b.read_partition(0, 0, timeout_s=10.0))
            assert sum(b.num_rows for b in out) == 8
            events = trace.get_tracer().to_chrome_trace()["traceEvents"]
            fetch = {e["args"]["span_id"] for e in events
                     if e.get("name") == "shuffle_fetch"}
            serve = {e["args"]["span_id"] for e in events
                     if str(e.get("name", "")).startswith("shuffle_serve")}
            assert fetch and fetch & serve, (fetch, serve)
        finally:
            EndpointRegistry.reset()

    def test_span_ids_are_unique(self):
        ids = {netplane.next_span_id() for _ in range(100)}
        assert len(ids) == 100


# ---------------------------------------------------------------------------
# end-to-end: a real multi-partition exchange through the session
# ---------------------------------------------------------------------------

def _shuffle_df(s):
    return (s.create_dataframe(
                {"k": [i % 7 for i in range(2000)],
                 "v": [float(i) for i in range(2000)]}, num_partitions=2)
            .group_by("k").agg(F.sum("v").alias("sv")))


class TestEndToEnd:
    def test_session_rollup_and_zero_extra_flushes(self):
        from spark_rapids_tpu.columnar import pending
        s = TpuSession(TpuConf({}))
        df = _shuffle_df(s)
        df.to_arrow()                                  # warm
        df.to_arrow()
        net_on = s.last_query_netplane
        assert net_on["edges"] > 0 and net_on["blocks"] > 0
        assert net_on["host_drop_tax_ms"] > 0
        assert sum(net_on["phases_ms"].values()) == pytest.approx(
            net_on["exchange_wall_ms"], rel=0.01, abs=0.02)
        assert net_on["top_edges"]
        flushes_on = s.last_query_flushes
        f0 = pending.FLUSH_COUNT
        df.to_arrow()
        assert pending.FLUSH_COUNT - f0 == flushes_on
        # the acceptance contract: disabling the plane changes NOTHING
        # about device flushes — an exact FLUSH_COUNT delta
        netplane.configure(TpuConf({
            "spark.rapids.tpu.obs.net.enabled": False}))
        df.to_arrow()
        assert s.last_query_flushes == flushes_on
        assert s.last_query_netplane["blocks"] == 0    # plane was off

    def test_event_log_record_carries_netplane(self, tmp_path):
        from spark_rapids_tpu.tools.events import read_event_log
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        _shuffle_df(s).to_arrow()
        recs = list(read_event_log(log))
        assert recs
        rec = recs[-1]
        assert rec["host_drop_tax_ms"] == \
            rec["shuffle_netplane"]["host_drop_tax_ms"] > 0
        sn = rec["shuffle_netplane"]
        assert sn["edges"] > 0 and sn["top_edges"]
        assert set(sn["phases_ms"]) == set(netplane.PHASES)


# ---------------------------------------------------------------------------
# surfaces: Prometheus, Service.stats(), tools/report.py
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_prometheus_exposition_covers_shuffle_families(self):
        netplane.note_serialize(1, 0, 0, rows=1, nbytes=64, dur_ns=MS)
        netplane.note_conn("dial")
        netplane.note_fetch("exec-x", 2 * MS, 64)
        text = render_text(get_registry())
        for series in (
                'tpu_shuffle_host_drop_seconds_total{phase="serialize"}',
                'tpu_shuffle_conn_events_total{event="dial"}',
                'tpu_shuffle_fetch_seconds_bucket',
                "tpu_shuffle_edges_tracked 1",
                "tpu_shuffle_pending_fetches 0"):
            assert series in text, series

    def test_stats_section_shape(self):
        netplane.note_serialize(2, 1, 0, rows=3, nbytes=30, dur_ns=MS)
        netplane.note_fetch("exec-y", MS, 30)
        sec = netplane.stats_section()
        assert sec["enabled"] and sec["edges_tracked"] == 1
        assert set(sec["host_drop"]["phases_ms"]) == set(netplane.PHASES)
        assert sec["connections"] == {"dial": 0, "reuse": 0, "reset": 0}
        assert sec["bounce"] == {"free": 0, "total": 0}
        assert sec["fetch_peers"]["exec-y"]["count"] == 1
        assert sec["fetch_peers"]["exec-y"]["avg_ms"] == \
            pytest.approx(1.0, abs=1e-6)
        assert sec["top_edges"][0]["rows"] == 3

    def test_pending_fetch_gauge_tracks_inflight(self):
        assert netplane.pending_fetches() == 0
        netplane.fetch_begun()
        netplane.fetch_begun()
        assert netplane.pending_fetches() == 2
        netplane.fetch_done()
        netplane.fetch_done()
        assert netplane.pending_fetches() == 0

    def test_report_renders_shuffle_section(self):
        from spark_rapids_tpu.tools.report import shuffle_lines
        rec = {"shuffle_netplane": {
            "host_drop_tax_ms": 4.0, "exchange_wall_ms": 16.0,
            "wire_MBps": 100.0, "edge_skew": 1.5, "edges": 2, "blocks": 3,
            "phases_ms": {"serialize": 2.0, "dwell": 12.0, "wire": 1.0,
                          "deserialize": 1.0},
            "top_edges": [{"shuffle_id": 0, "map_id": 1, "reduce_id": 2,
                           "rows": 10, "bytes": 1000, "batches": 1}],
            "fetch_peers": {"exec-z": {"count": 2, "avg_ms": 1.5,
                                       "max_ms": 2.0, "bytes": 2000}}}}
        text = "\n".join(shuffle_lines(rec))
        assert "host_drop_tax_ms=4.0" in text
        for phase in netplane.PHASES:
            assert phase in text
        assert "dwell          75.0%" in text      # 12 of 16ms
        assert "top edges (map -> reduce):" in text
        assert "exec-z" in text

    def test_report_tolerates_pre_netplane_records(self):
        from spark_rapids_tpu.tools.report import shuffle_lines
        (line,) = shuffle_lines({"query_id": "old"})
        assert "no shuffle netplane recorded" in line


# ---------------------------------------------------------------------------
# satellites: compression counters, heartbeat liveness, client.close()
# ---------------------------------------------------------------------------

class TestCompressionCounters:
    def test_incompressible_data_counted_and_bounded(self):
        from spark_rapids_tpu.obs.registry import SHUFFLE_COMPRESSION_BYTES
        from spark_rapids_tpu.shuffle.compression import get_codec
        codec = get_codec("zlib")
        raw_c = SHUFFLE_COMPRESSION_BYTES.labels(codec="zlib",
                                                 direction="raw")
        comp_c = SHUFFLE_COMPRESSION_BYTES.labels(codec="zlib",
                                                  direction="compressed")
        raw0, comp0 = raw_c.value, comp_c.value
        data = os.urandom(1 << 16)
        out = codec.compress(data)
        # regression: incompressible payload must not blow up in size
        assert len(out) <= len(data) + len(data) // 64 + 256
        assert raw_c.value - raw0 == len(data)
        assert comp_c.value - comp0 == len(out)
        back = codec.decompress(out, len(data))
        assert back == data
        # decompress counts the same traffic once more, same directions
        assert raw_c.value - raw0 == 2 * len(data)
        assert comp_c.value - comp0 == 2 * len(out)

    def test_compressible_data_shows_ratio_win(self):
        from spark_rapids_tpu.shuffle.compression import get_codec
        codec = get_codec("zlib")
        data = b"spark-rapids-tpu" * 4096
        out = codec.compress(data)
        assert len(out) < len(data) // 10

    def test_codec_traffic_feeds_per_exchange_ratio_and_report(self):
        from spark_rapids_tpu.shuffle.compression import get_codec
        from spark_rapids_tpu.tools.report import shuffle_lines
        marker = netplane.begin_query()
        codec = get_codec("zlib")
        data = b"spark-rapids-tpu" * 4096
        out = codec.compress(data)
        summary = netplane.query_summary(marker)
        comp = summary["compression"]
        assert comp["raw_bytes"] == len(data)
        assert comp["compressed_bytes"] == len(out)
        assert comp["ratio"] == pytest.approx(len(data) / len(out),
                                              abs=0.01)
        assert comp["codecs"] == ["zlib"]
        assert netplane.stats_section()["compression"]["raw_bytes"] \
            >= len(data)
        text = "\n".join(shuffle_lines({"shuffle_netplane": summary}))
        assert "compression [zlib]" in text and "ratio=" in text


class TestHeartbeatLiveness:
    def test_peer_stats_flags_stale_after_three_intervals(self):
        from spark_rapids_tpu.shuffle import (PeerInfo,
                                              RapidsShuffleHeartbeatManager)
        mgr = RapidsShuffleHeartbeatManager(heartbeat_interval_s=0.02,
                                            timeout_s=30.0)
        mgr.register_executor(PeerInfo("exec-a"))
        stats = mgr.peer_stats()
        assert not stats["exec-a"]["stale"]
        time.sleep(0.08)                       # > 3 * 0.02s interval
        stats = mgr.peer_stats()
        assert stats["exec-a"]["stale"]
        assert stats["exec-a"]["last_seen_age_s"] >= 0.06
        # a beat un-stales the peer, and the manager feeds stats()
        mgr.executor_heartbeat("exec-a")
        assert not mgr.peer_stats()["exec-a"]["stale"]
        assert netplane.stats_section()["peers"]["exec-a"]["stale"] is False

    def test_beat_observes_rtt_histogram(self):
        from spark_rapids_tpu.shuffle import (
            PeerInfo, RapidsShuffleHeartbeatEndpoint,
            RapidsShuffleHeartbeatManager)

        class _NoTransport:
            def connect(self, peer):
                pass

        mgr = RapidsShuffleHeartbeatManager(heartbeat_interval_s=0.02)
        ep = RapidsShuffleHeartbeatEndpoint(mgr, _NoTransport(),
                                            PeerInfo("exec-rtt"))
        ep.beat()
        text = render_text(get_registry())
        assert 'tpu_shuffle_peer_rtt_seconds_count{peer="exec-rtt"}' in text


class _ScriptedConnection:
    """Minimal scripted ClientConnection (the Mockito-mock pattern)."""

    def __init__(self):
        from spark_rapids_tpu.shuffle import ClientConnection
        ClientConnection.__init__(self, "mock-peer")
        self.data_handler = None
        self.metadata_requests = []
        self.transfer_requests = []

    def register_data_handler(self, handler):
        self.data_handler = handler

    def unregister_data_handler(self, handler):
        if self.data_handler is handler:
            self.data_handler = None

    def request_metadata(self, req, handler):
        from spark_rapids_tpu.shuffle import Transaction
        tx = Transaction()
        self.metadata_requests.append((req, handler, tx))
        return tx

    def request_transfer(self, req, handler):
        from spark_rapids_tpu.shuffle import Transaction
        tx = Transaction()
        self.transfer_requests.append((req, handler, tx))
        return tx


class _Collecting:
    def __init__(self):
        self.batches, self.errors, self.expected = [], [], None

    def start(self, expected):
        self.expected = expected

    def batch_received(self, handle):
        self.batches.append(handle)

    def transfer_error(self, message):
        self.errors.append(message)


class TestClientCloseRegression:
    def test_close_errors_pending_receives(self):
        # the bug the pending-fetch gauge surfaced: close() silently
        # dropped in-flight tables, leaving fetch waiters hung forever
        import numpy as np
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.shuffle import (BlockIdSpec, MetadataResponse,
                                              RapidsShuffleClient,
                                              TransferResponse,
                                              build_table_meta)
        conn = _ScriptedConnection()
        client = RapidsShuffleClient(conn)
        handler = _Collecting()
        span_id = client.do_fetch([BlockIdSpec(0, 0, 1)], handler)
        assert span_id > 0
        src = ColumnarBatch.from_pydict(
            {"a": np.arange(16, dtype=np.int64)})
        meta, blob = build_table_meta(src)
        (req, meta_cb, _tx) = conn.metadata_requests[0]
        assert req.span_id == span_id          # context rides the request
        meta_cb(MetadataResponse(req.request_id, [[meta]]))
        (treq, transfer_cb, _ttx) = conn.transfer_requests[0]
        transfer_cb(TransferResponse(treq.request_id, True))
        # only half the blob lands before teardown
        conn.data_handler(treq.tags[0], 0, blob[:len(blob) // 2])
        client.close()
        assert handler.errors and "closed" in handler.errors[0]
        assert not handler.batches
        client.close()                          # idempotent

    def test_fetch_after_close_errors_immediately(self):
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.shuffle import (BlockIdSpec, MetadataResponse,
                                              RapidsShuffleClient,
                                              build_table_meta)
        import numpy as np
        conn = _ScriptedConnection()
        client = RapidsShuffleClient(conn)
        handler = _Collecting()
        client.do_fetch([BlockIdSpec(0, 0, 0)], handler)
        client.close()
        # the metadata response races past close(): waiters still error
        meta, _ = build_table_meta(ColumnarBatch.from_pydict(
            {"a": np.arange(4, dtype=np.int64)}))
        (req, meta_cb, _tx) = conn.metadata_requests[0]
        meta_cb(MetadataResponse(req.request_id, [[meta]]))
        assert handler.errors and "closed" in handler.errors[0]
