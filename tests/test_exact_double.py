"""exactDouble mode: DOUBLE as IEEE-754 bits with softfloat kernels.

Reference contract: bit-for-bit DOUBLE semantics (GpuCast.scala /
arithmetic.scala via cuDF's native f64).  The chip's emulated f64 is an
f32 pair (~1e+/-38 range), so these tests use magnitudes like 1e300
that CANNOT survive the emulated path — passing proves the bits path
is actually in use end to end (scan -> filter -> project -> aggregate
-> sort -> collect).
"""
import math

import numpy as np
import pytest

from harness import with_cpu_session, with_tpu_session

CONF = {"spark.rapids.tpu.sql.exactDouble.enabled": True}

BIG = [1e300, -1e300, 4.9e-324, 2.2250738585072014e-308,
       3.141592653589793, -0.0, 0.0, math.inf, -math.inf, 1.5e308]


def _bits(x):
    return np.float64(x).view(np.int64).item() if x is not None else None


class TestExactDouble:
    def test_roundtrip_extreme_values(self):
        def q(s):
            df = s.create_dataframe({"x": np.array(BIG, np.float64)})
            return df
        rows = with_tpu_session(lambda s: q(s).collect(), CONF)
        assert [_bits(r[0]) for r in rows] == [_bits(v) for v in BIG]

    def test_filter_and_compare_beyond_f32_range(self):
        def q(s):
            from spark_rapids_tpu.api import functions as F
            df = s.create_dataframe({
                "x": np.array([1e300, 1e250, -1e300, 5.0, 1e38],
                              np.float64)})
            return df.filter(F.col("x") > 1e249)
        tpu = sorted(_bits(r[0]) for r in
                     with_tpu_session(lambda s: q(s).collect(), CONF))
        cpu = sorted(_bits(r[0]) for r in
                     with_cpu_session(lambda s: q(s).collect()))
        assert tpu == cpu and len(tpu) == 2

    def test_arithmetic_bit_exact(self):
        rng = np.random.default_rng(8)
        x = rng.standard_normal(500) * 1e290
        y = rng.standard_normal(500) * 3.7 + 1.0

        def q(s):
            from spark_rapids_tpu.api import functions as F
            df = s.create_dataframe({"x": x, "y": y})
            return df.select(
                (F.col("x") * F.col("y")).alias("m"),
                (F.col("x") + F.col("y")).alias("a"),
                (F.col("x") - F.col("y")).alias("sb"),
                (F.col("x") / F.col("y")).alias("d"),
                (-F.col("x")).alias("n"),
                F.abs(F.col("x")).alias("ab"))
        tpu = with_tpu_session(lambda s: q(s).collect(), CONF)
        want = list(zip(x * y, x + y, x - y, x / y, -x, np.abs(x)))
        for got, exp in zip(tpu, want):
            assert [_bits(g) for g in got] == [_bits(e) for e in exp]

    def test_aggregate_sum_min_max_beyond_f32(self):
        # powers of two spanning few bits: the sum is EXACT in any
        # order, at a magnitude the emulated path cannot even store
        k = np.array([0, 0, 1, 1, 1, 0], np.int64)
        v = np.array([2.0**1000, 2.0**1001, 2.0**1002, 2.0**999,
                      2.0**998, -(2.0**1001)])

        def q(s):
            from spark_rapids_tpu.api import functions as F
            return (s.create_dataframe({"k": k, "v": v})
                     .group_by("k")
                     .agg(F.sum("v").alias("sv"), F.min("v").alias("mn"),
                          F.max("v").alias("mx"), F.avg("v").alias("av"),
                          F.count().alias("c")))
        tpu = sorted(with_tpu_session(lambda s: q(s).collect(), CONF))
        for kk, sv, mn, mx, av, c in tpu:
            sel = v[k == kk]
            assert _bits(sv) == _bits(np.sum(sel))
            assert _bits(mn) == _bits(np.min(sel))
            assert _bits(mx) == _bits(np.max(sel))
            assert _bits(av) == _bits(np.sum(sel) / len(sel))
            assert c == len(sel)

    def test_sort_total_order_with_specials(self):
        vals = [1e300, -1e300, math.nan, math.inf, -math.inf, -0.0,
                0.0, 1e-300, 5.0]

        def q(s):
            from spark_rapids_tpu.api import functions as F
            df = s.create_dataframe({"x": np.array(vals, np.float64)})
            return df.sort(F.col("x"))
        tpu = [r[0] for r in with_tpu_session(lambda s: q(s).collect(),
                                              CONF)]
        # Spark total order: -inf < finite < inf < NaN; -0.0 == 0.0
        expect = [-math.inf, -1e300, -0.0, 0.0, 1e-300, 5.0, 1e300,
                  math.inf, math.nan]
        for g, e in zip(tpu, expect):
            if math.isnan(e):
                assert math.isnan(g)
            else:
                assert g == e

    def test_cast_roundtrip(self):
        def q(s):
            from spark_rapids_tpu.api import functions as F
            df = s.create_dataframe({
                "i": np.array([0, 1, -7, 2**53, -(2**53)], np.int64)})
            d = df.with_column("d", F.col("i").cast("double"))
            return d.with_column("back", F.col("d").cast("long"))
        rows = with_tpu_session(lambda s: q(s).collect(), CONF)
        for i, d, back in rows:
            assert d == float(i)
            assert back == i

    def test_join_on_double_key(self):
        lk = np.array([1e300, 2e300, 5.0, -0.0], np.float64)
        rk = np.array([2e300, 0.0, 7.0], np.float64)

        def q(s):
            left = s.create_dataframe({"k": lk, "a": np.arange(4)})
            right = s.create_dataframe({"rk": rk,
                                        "b": np.arange(3) * 10})
            return left.join(right, left["k"] == right["rk"], "inner")
        rows = sorted(with_tpu_session(lambda s: q(s).collect(), CONF))
        # 2e300 matches; -0.0 matches 0.0 (Spark float equality)
        keys = sorted(_bits(abs(r[0])) for r in rows)
        assert len(rows) == 2
        assert _bits(2e300) in keys
