"""Extended string expression tests (reference: string_test.py breadth)."""
import pytest
from spark_rapids_tpu.api import functions as F

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import StringGen, IntGen, gen_df

N = 120


class TestStringsExtra:
    def test_replace(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="abcab ")}, N)
            .select(F.replace("s", "ab", "X").alias("r")))

    def test_reverse_ascii(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen()}, N)
            .select(F.reverse("s").alias("r")))

    def test_reverse_unicode(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="aöü日")}, N)
            .select(F.reverse("s").alias("r")))

    def test_pad_repeat(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(max_len=6)}, N)
            .select(F.lpad("s", 8, "*").alias("l"),
                    F.rpad("s", 8, "-").alias("r"),
                    F.repeat("s", 2).alias("rep")))

    def test_initcap_instr(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="ab AB")}, N)
            .select(F.initcap("s").alias("ic"),
                    F.instr("s", "b").alias("pos")))

    def test_concat_ws(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": StringGen(), "b": StringGen()}, N)
            .select(F.concat_ws("-", "a", "b").alias("c")))

    def test_regexp(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="ab12")}, N)
            .select(F.regexp_replace("s", "[0-9]+", "#").alias("rr"),
                    F.regexp_extract("s", "([0-9]+)", 1).alias("rx")))


class TestDeviceMultiSegmentLike:
    """Device path for general %-only LIKE patterns (ordered segment
    search via find_in_row) — oracle vs python re."""

    @pytest.mark.parametrize("pattern", [
        "a%b", "%a%b%", "ab%cd%ef", "a%b%c", "x%", "%x", "%mid%dle%",
        "a%a", "%%", "abc"])
    def test_patterns_match_re_oracle(self, pattern):
        import re as _re
        from spark_rapids_tpu.columnar.column import StringColumn
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar import Schema, Field, dtypes as T
        from spark_rapids_tpu.expr.string_ops import Like, _like_to_regex
        from spark_rapids_tpu.expr.core import (AttributeReference,
                                                Literal)
        vals = ["ab", "aXb", "abcdef", "ab-cd-ef", "abcdXef", "", "a",
                "aa", "xax", "middle", "mid-dle", "ddmiddledd",
                "bXa", None, "ababab", "x", "aba"]
        col = StringColumn.from_pylist(vals)
        batch = ColumnarBatch(Schema([Field("s", T.STRING)]), [col],
                              len(vals))
        e = Like(AttributeReference("s", T.STRING, True),
                 Literal(pattern, T.STRING)).bind(batch.schema)
        got = e.columnar_eval(batch)
        rx = _re.compile(_like_to_regex(pattern, "\\"), _re.DOTALL)
        out = got.data.astype(bool) & got.validity
        for i, v in enumerate(vals):
            want = v is not None and rx.fullmatch(v) is not None
            assert bool(out[i]) == want, (pattern, v)

    def test_host_regex_counter_and_device_path(self):
        from spark_rapids_tpu.expr import string_ops as so
        from spark_rapids_tpu.columnar.column import StringColumn
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar import Schema, Field, dtypes as T
        from spark_rapids_tpu.expr.core import (AttributeReference,
                                                Literal)
        col = StringColumn.from_pylist(["abc", "adc", "xbz"])
        batch = ColumnarBatch(Schema([Field("s", T.STRING)]), [col], 3)
        ref = AttributeReference("s", T.STRING, True)
        before = so.HOST_REGEX_EVALS["count"]
        # %-only pattern: device path, no counter bump
        so.Like(ref, Literal("a%c", T.STRING)).bind(batch.schema) \
            .columnar_eval(batch)
        assert so.HOST_REGEX_EVALS["count"] == before
        # underscore forces the host engine and bumps the counter
        so.Like(ref, Literal("a_c", T.STRING)).bind(batch.schema) \
            .columnar_eval(batch)
        assert so.HOST_REGEX_EVALS["count"] == before + 1
