"""Extended string expression tests (reference: string_test.py breadth)."""
from spark_rapids_tpu.api import functions as F

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import StringGen, IntGen, gen_df

N = 120


class TestStringsExtra:
    def test_replace(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="abcab ")}, N)
            .select(F.replace("s", "ab", "X").alias("r")))

    def test_reverse_ascii(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen()}, N)
            .select(F.reverse("s").alias("r")))

    def test_reverse_unicode(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="aöü日")}, N)
            .select(F.reverse("s").alias("r")))

    def test_pad_repeat(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(max_len=6)}, N)
            .select(F.lpad("s", 8, "*").alias("l"),
                    F.rpad("s", 8, "-").alias("r"),
                    F.repeat("s", 2).alias("rep")))

    def test_initcap_instr(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="ab AB")}, N)
            .select(F.initcap("s").alias("ic"),
                    F.instr("s", "b").alias("pos")))

    def test_concat_ws(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": StringGen(), "b": StringGen()}, N)
            .select(F.concat_ws("-", "a", "b").alias("c")))

    def test_regexp(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"s": StringGen(charset="ab12")}, N)
            .select(F.regexp_replace("s", "[0-9]+", "#").alias("rr"),
                    F.regexp_extract("s", "([0-9]+)", 1).alias("rx")))
