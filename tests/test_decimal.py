"""Decimal64 tests — reference: decimalExpressions.scala + the
DECIMAL64-only gate (GpuOverrides.scala:659)."""
from decimal import Decimal

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.config import TpuConf

from harness import assert_tpu_and_cpu_are_equal_collect


def _dec_table():
    return pa.table({
        "a": pa.array([Decimal("1.50"), Decimal("-2.25"), None,
                       Decimal("1000.01")], pa.decimal128(10, 2)),
        "b": pa.array([Decimal("0.5"), Decimal("1.5"), Decimal("2.0"),
                       None], pa.decimal128(8, 1)),
        "k": [1, 1, 2, 2],
    })


class TestDecimal:
    def test_roundtrip(self):
        s = TpuSession(TpuConf({}))
        df = s.create_dataframe(_dec_table())
        rows = df.collect()
        assert rows[0][0] == Decimal("1.50")
        assert rows[2][0] is None

    def test_add_mixed_scale(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .select((F.col("a") + F.col("b")).alias("s"),
                    (F.col("a") - F.col("b")).alias("d")))

    def test_multiply(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .select((F.col("a") * F.col("b")).alias("m")))

    def test_sum_group(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .group_by("k").agg(F.sum("a").alias("sa")))

    def test_compare_and_sort(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .filter(F.col("a") > 0).sort("a"),
            ignore_order=False)

    def test_decimal_disabled_falls_back(self):
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.decimalType.enabled": False}))
        df = s.create_dataframe(_dec_table()).select(
            (F.col("a") + F.col("b")).alias("s"))
        df.collect()  # runs on CPU engine
        assert any("decimal" in f for f in s._last_planner.fallbacks)

    def test_precision_over_18_rejected(self):
        with pytest.raises(ValueError):
            T.DecimalType(20, 2)
