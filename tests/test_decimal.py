"""Decimal64 tests — reference: decimalExpressions.scala + the
DECIMAL64-only gate (GpuOverrides.scala:659)."""
from decimal import Decimal

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.config import TpuConf

from harness import assert_tpu_and_cpu_are_equal_collect


def _dec_table():
    return pa.table({
        "a": pa.array([Decimal("1.50"), Decimal("-2.25"), None,
                       Decimal("1000.01")], pa.decimal128(10, 2)),
        "b": pa.array([Decimal("0.5"), Decimal("1.5"), Decimal("2.0"),
                       None], pa.decimal128(8, 1)),
        "k": [1, 1, 2, 2],
    })


class TestDecimal:
    def test_roundtrip(self):
        s = TpuSession(TpuConf({}))
        df = s.create_dataframe(_dec_table())
        rows = df.collect()
        assert rows[0][0] == Decimal("1.50")
        assert rows[2][0] is None

    def test_add_mixed_scale(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .select((F.col("a") + F.col("b")).alias("s"),
                    (F.col("a") - F.col("b")).alias("d")))

    def test_multiply(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .select((F.col("a") * F.col("b")).alias("m")))

    def test_sum_group(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .group_by("k").agg(F.sum("a").alias("sa")))

    def test_compare_and_sort(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(_dec_table())
            .filter(F.col("a") > 0).sort("a"),
            ignore_order=False)

    def test_decimal_disabled_falls_back(self):
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.decimalType.enabled": False}))
        df = s.create_dataframe(_dec_table()).select(
            (F.col("a") + F.col("b")).alias("s"))
        df.collect()  # runs on CPU engine
        assert any("decimal" in f for f in s._last_planner.fallbacks)

    def test_precision_over_18_rejected(self):
        with pytest.raises(ValueError):
            T.DecimalType(20, 2)


class TestDecimalComparisonPromotion:
    """Mismatched-scale and int-vs-decimal comparisons must stay exact
    (int64 rescale, not a float64 round-trip)."""

    def test_mismatched_scale_exact(self):
        import pyarrow as pa
        from decimal import Decimal
        from harness import assert_tpu_and_cpu_are_equal_collect
        from spark_rapids_tpu.api import functions as F

        def fn(s):
            t = pa.table({
                "a": pa.array([Decimal("11111111111111.11"),
                               Decimal("2.50")],
                              type=pa.decimal128(16, 2)),
                "b": pa.array([Decimal("11111111111111.112"),
                               Decimal("2.500")],
                              type=pa.decimal128(17, 3)),
            })
            return s.create_dataframe(t).select(
                (F.col("a") == F.col("b")).alias("eq"),
                (F.col("a") < F.col("b")).alias("lt"))
        rows = assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=False)
        # 16-digit values differing at the 3rd decimal must NOT collapse
        assert rows[0] == (False, True)
        assert rows[1] == (True, False)

    def test_int_vs_decimal_above_2_53(self):
        import pyarrow as pa
        from decimal import Decimal
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F
        v = 9007199254740993  # 2^53 + 1: not representable in float64

        def fn(s):
            t = pa.table({"d": pa.array([Decimal(v), Decimal(v + 2)],
                                        type=pa.decimal128(18, 0))})
            return s.create_dataframe(t).filter(
                F.col("d") == F.lit(v)).collect()
        rows = with_tpu_session(fn)
        assert len(rows) == 1

    def test_decimal_to_decimal_rescale_cast(self):
        import pyarrow as pa
        from decimal import Decimal
        from harness import assert_tpu_and_cpu_are_equal_collect
        from spark_rapids_tpu.api import functions as F
        from spark_rapids_tpu.columnar import dtypes as T

        def fn(s):
            t = pa.table({"d": pa.array(
                [Decimal("12.345"), Decimal("-7.005"), None],
                type=pa.decimal128(10, 3))})
            return s.create_dataframe(t).select(
                F.col("d").cast(T.DecimalType(12, 5)).alias("up"),
                F.col("d").cast(T.DecimalType(10, 1)).alias("down"),
                F.col("d").cast("bigint").alias("i"))
        assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=False)
