"""Window function tests — reference: window_function_test.py pattern."""
import pytest

from spark_rapids_tpu.api import functions as F

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import IntGen, FloatGen, KeyGen, gen_df

N = 200


class TestWindow:
    def test_row_number(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=8),
                                 "v": IntGen(lo=-100, hi=100)}, N)
            .with_window("rn", F.row_number(), partition_by=["k"],
                         order_by=["v", "k"]))

    def test_rank_dense_rank(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=6),
                                 "v": KeyGen(cardinality=10,
                                             null_ratio=0.0)}, N)
            .with_window("rk", F.rank(), partition_by=["k"],
                         order_by=["v"]))

    def test_lead_lag(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=5),
                                 "v": IntGen(lo=0, hi=1000,
                                             null_ratio=0.0),
                                 "x": FloatGen(null_ratio=0.2)}, N)
            .with_window("ld", F.lead("x"), partition_by=["k"],
                         order_by=["v", "x"])
            .with_window("lg", F.lag("x"), partition_by=["k"],
                         order_by=["v", "x"]))

    def test_partition_aggregate(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=7),
                                 "v": FloatGen(no_nans=True)}, N)
            .with_window("s", F.sum("v"), partition_by=["k"],
                         frame=("rows", None, None))
            .with_window("c", F.count("v"), partition_by=["k"],
                         frame=("rows", None, None)))

    def test_running_sum(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=4),
                                 "o": IntGen(lo=0, hi=10**6,
                                             null_ratio=0.0),
                                 "v": IntGen(lo=-50, hi=50)}, N)
            .with_window("rs", F.sum("v"), partition_by=["k"],
                         order_by=["o"], frame=("rows", None, 0)))

    def test_global_window(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"v": IntGen(lo=0, hi=100,
                                             null_ratio=0.0)}, 50)
            .with_window("rn", F.row_number(), partition_by=[],
                         order_by=["v"]))


class TestFrames:
    """Bounded ROWS and RANGE frames vs the exact CPU oracle."""

    def _df(self, s, n=60):
        import numpy as np
        rng = np.random.default_rng(9)
        return s.create_dataframe({
            "g": rng.integers(0, 5, n).astype(np.int64),
            "o": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.integers(-50, 50, n).astype(np.int64),
        })

    def test_bounded_rows_frame(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s).with_window(
                "w", F.sum("v"), partition_by=["g"], order_by=["o"],
                frame=("rows", -2, 1)))

    def test_range_frame_sum(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s).with_window(
                "w", F.sum("v"), partition_by=["g"], order_by=["o"],
                frame=("range", -5, 5)))

    def test_range_frame_count_avg(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s)
            .with_window("c", F.count("v"), partition_by=["g"],
                         order_by=["o"], frame=("range", None, 0))
            .with_window("a", F.avg("v"), partition_by=["g"],
                         order_by=["o"], frame=("range", -3, 3)))

    def test_range_frame_desc(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s).with_window(
                "w", F.sum("v"), partition_by=["g"],
                order_by=[F.col("o").desc()], frame=("range", -4, 2)))

    def test_range_frame_with_null_order(self):
        from spark_rapids_tpu.api import functions as F

        def fn(s):
            df = s.create_dataframe({
                "g": [1, 1, 1, 1, 2, 2],
                "o": [1, None, 3, None, 2, 5],
                "v": [10, 20, 30, 40, 50, 60],
            })
            return df.with_window(
                "w", F.sum("v"), partition_by=["g"], order_by=["o"],
                frame=("range", -2, 2))
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_range_frame_half_unbounded_with_null_order(self):
        """UNBOUNDED sides reach the partition edge and take the
        null-order block in with them (Spark RANGE semantics)."""
        from spark_rapids_tpu.api import functions as F

        def fn(frame, order_desc=False):
            def run(s):
                df = s.create_dataframe({
                    "g": [1, 1, 1, 1, 2, 2],
                    "o": [1, None, 3, None, 2, 5],
                    "v": [10, 20, 30, 40, 50, 60],
                })
                ob = [F.col("o").desc()] if order_desc else ["o"]
                return df.with_window(
                    "w", F.sum("v"), partition_by=["g"], order_by=ob,
                    frame=frame)
            return run
        assert_tpu_and_cpu_are_equal_collect(fn(("range", None, 0)))
        assert_tpu_and_cpu_are_equal_collect(fn(("range", -1, None)))
        assert_tpu_and_cpu_are_equal_collect(
            fn(("range", None, 1), order_desc=True))


class TestWindowCompleteness:
    """Round-4 window breadth (GpuWindowExpression.scala parity):
    ntile / percent_rank / cume_dist, bounded min/max frames, RANGE
    min/max, collect_list over windows."""

    def test_ntile(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=5),
                                 "v": IntGen(lo=0, hi=1000,
                                             null_ratio=0.0)}, N)
            .with_window("nt", F.ntile(4), partition_by=["k"],
                         order_by=["v"]))

    def test_percent_rank_cume_dist(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=5),
                                 "v": KeyGen(cardinality=12,
                                             null_ratio=0.0)}, N)
            .with_window("pr", F.percent_rank(), partition_by=["k"],
                         order_by=["v"])
            .with_window("cd", F.cume_dist(), partition_by=["k"],
                         order_by=["v"]))

    def test_bounded_min_max_rows(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=6),
                                 "o": IntGen(lo=0, hi=10000,
                                             null_ratio=0.0),
                                 "v": IntGen(lo=-500, hi=500,
                                             null_ratio=0.15)}, N)
            .with_window("mn", F.min("v"), partition_by=["k"],
                         order_by=["o", "v"], frame=("rows", -3, 2))
            .with_window("mx", F.max("v"), partition_by=["k"],
                         order_by=["o", "v"], frame=("rows", -2, None)))

    def test_range_min_max(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=4),
                                 "o": IntGen(lo=0, hi=60,
                                             null_ratio=0.1),
                                 "v": IntGen(lo=-500, hi=500,
                                             null_ratio=0.1)}, N)
            .with_window("mn", F.min("v"), partition_by=["k"],
                         order_by=["o"], frame=("range", -5, 5))
            .with_window("mx", F.max("v"), partition_by=["k"],
                         order_by=["o"], frame=("range", None, 3)))

    def test_collect_list_window(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=5),
                                 "o": IntGen(lo=0, hi=100000,
                                             null_ratio=0.0),
                                 "v": IntGen(lo=0, hi=50,
                                             null_ratio=0.2)}, N)
            .with_window("cl", F.collect_list("v"), partition_by=["k"],
                         order_by=["o", "v"], frame=("rows", -2, 1)))

    def test_collect_list_window_unbounded(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=4),
                                 "o": IntGen(lo=0, hi=100000,
                                             null_ratio=0.0),
                                 "v": IntGen(lo=0, hi=50,
                                             null_ratio=0.1)}, N)
            .with_window("cl", F.collect_list("v"), partition_by=["k"],
                         order_by=["o", "v"],
                         frame=("rows", None, None)))

    def test_sql_window_completeness(self):
        """ntile/percent_rank/cume_dist + bounded ROWS min + bounded
        RANGE max + windowed collect_list through session.sql()."""
        import numpy as np
        from harness import with_cpu_session, with_tpu_session
        rng = np.random.default_rng(5)
        data = {"k": rng.integers(0, 5, 200).astype(np.int64),
                "o": rng.integers(0, 50, 200).astype(np.int64),
                "v": rng.integers(-50, 50, 200).astype(np.int64)}
        sql = """
          select k, o, v,
                 ntile(3) over (partition by k order by o, v) nt,
                 percent_rank() over (partition by k order by o) pr,
                 cume_dist() over (partition by k order by o) cd,
                 min(v) over (partition by k order by o, v
                              rows between 3 preceding and 2 following)
                   mn,
                 max(v) over (partition by k order by o
                              range between 5 preceding and 5 following)
                   mx,
                 collect_list(v) over (partition by k order by o, v
                              rows between 2 preceding and current row)
                   cl
          from t order by k, o, v"""

        def run(s):
            s.create_dataframe(data).create_or_replace_temp_view("t")
            return s.sql(sql).collect()
        cpu = with_cpu_session(run)
        tpu = with_tpu_session(run)
        assert len(cpu) == len(tpu) == 200
        for a, b in zip(tpu, cpu):
            for x, y in zip(a, b):
                if isinstance(x, float):
                    assert abs(x - y) < 1e-9
                else:
                    assert x == y

    def test_mixed_key_window_collapse_warns(self):
        """A plan that coalesces to one partition for mixed-key windows
        must say so (round-3 Weak #9), not silently go single-stream."""
        import numpy as np
        from harness import with_tpu_session
        rng = np.random.default_rng(3)

        def run(s):
            df = s.create_dataframe(
                {"a": rng.integers(0, 5, 100).astype(np.int64),
                 "b": rng.integers(0, 5, 100).astype(np.int64),
                 "v": rng.integers(0, 50, 100).astype(np.int64)},
                num_partitions=4)
            df.create_or_replace_temp_view("t")
            # ONE window node with MIXED partition keys -> the planner
            # coalesces to a single stream and must warn
            s.sql("""
              select a, b, v,
                     row_number() over (partition by a order by v) r1,
                     row_number() over (partition by b order by v) r2
              from t""").collect()
            return s._last_planner.parallelism_warnings
        warnings = with_tpu_session(run)
        assert any("single-stream" in w for w in warnings)

    def test_rank_descending_with_nulls(self):
        """DESC single-key rank through BOTH engines (the CPU oracle
        previously ranked by ascending value, inverting DESC ranks)."""
        import numpy as np
        from harness import with_cpu_session, with_tpu_session
        k = [0, 0, 0, 1, 1, 1, 1]
        v = [3, 1, 1, None, 5, 5, 2]

        def run(s):
            df = s.create_dataframe({"k": np.array(k, dtype=np.int64),
                                     "v": v})
            df.create_or_replace_temp_view("t")
            return sorted(s.sql(
                "select k, v, rank() over (partition by k "
                "order by v desc) r, dense_rank() over (partition by k "
                "order by v desc) d from t").collect(),
                key=lambda r: (r[0], r[2]))
        cpu = with_cpu_session(run)
        tpu = with_tpu_session(run)
        assert cpu == tpu
        # spot-check Spark semantics: [3,1,1] desc -> ranks [1,2,2]
        g0 = [(r[1], r[2], r[3]) for r in cpu if r[0] == 0]
        assert g0 == [(3, 1, 1), (1, 2, 2), (1, 2, 2)]
