"""Window function tests — reference: window_function_test.py pattern."""
import pytest

from spark_rapids_tpu.api import functions as F

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import IntGen, FloatGen, KeyGen, gen_df

N = 200


class TestWindow:
    def test_row_number(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=8),
                                 "v": IntGen(lo=-100, hi=100)}, N)
            .with_window("rn", F.row_number(), partition_by=["k"],
                         order_by=["v", "k"]))

    def test_rank_dense_rank(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=6),
                                 "v": KeyGen(cardinality=10,
                                             null_ratio=0.0)}, N)
            .with_window("rk", F.rank(), partition_by=["k"],
                         order_by=["v"]))

    def test_lead_lag(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=5),
                                 "v": IntGen(lo=0, hi=1000,
                                             null_ratio=0.0),
                                 "x": FloatGen(null_ratio=0.2)}, N)
            .with_window("ld", F.lead("x"), partition_by=["k"],
                         order_by=["v", "x"])
            .with_window("lg", F.lag("x"), partition_by=["k"],
                         order_by=["v", "x"]))

    def test_partition_aggregate(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=7),
                                 "v": FloatGen(no_nans=True)}, N)
            .with_window("s", F.sum("v"), partition_by=["k"],
                         frame=("rows", None, None))
            .with_window("c", F.count("v"), partition_by=["k"],
                         frame=("rows", None, None)))

    def test_running_sum(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=4),
                                 "o": IntGen(lo=0, hi=10**6,
                                             null_ratio=0.0),
                                 "v": IntGen(lo=-50, hi=50)}, N)
            .with_window("rs", F.sum("v"), partition_by=["k"],
                         order_by=["o"], frame=("rows", None, 0)))

    def test_global_window(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"v": IntGen(lo=0, hi=100,
                                             null_ratio=0.0)}, 50)
            .with_window("rn", F.row_number(), partition_by=[],
                         order_by=["v"]))
