"""Window function tests — reference: window_function_test.py pattern."""
import pytest

from spark_rapids_tpu.api import functions as F

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import IntGen, FloatGen, KeyGen, gen_df

N = 200


class TestWindow:
    def test_row_number(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=8),
                                 "v": IntGen(lo=-100, hi=100)}, N)
            .with_window("rn", F.row_number(), partition_by=["k"],
                         order_by=["v", "k"]))

    def test_rank_dense_rank(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=6),
                                 "v": KeyGen(cardinality=10,
                                             null_ratio=0.0)}, N)
            .with_window("rk", F.rank(), partition_by=["k"],
                         order_by=["v"]))

    def test_lead_lag(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=5),
                                 "v": IntGen(lo=0, hi=1000,
                                             null_ratio=0.0),
                                 "x": FloatGen(null_ratio=0.2)}, N)
            .with_window("ld", F.lead("x"), partition_by=["k"],
                         order_by=["v", "x"])
            .with_window("lg", F.lag("x"), partition_by=["k"],
                         order_by=["v", "x"]))

    def test_partition_aggregate(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=7),
                                 "v": FloatGen(no_nans=True)}, N)
            .with_window("s", F.sum("v"), partition_by=["k"],
                         frame=("rows", None, None))
            .with_window("c", F.count("v"), partition_by=["k"],
                         frame=("rows", None, None)))

    def test_running_sum(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"k": KeyGen(cardinality=4),
                                 "o": IntGen(lo=0, hi=10**6,
                                             null_ratio=0.0),
                                 "v": IntGen(lo=-50, hi=50)}, N)
            .with_window("rs", F.sum("v"), partition_by=["k"],
                         order_by=["o"], frame=("rows", None, 0)))

    def test_global_window(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"v": IntGen(lo=0, hi=100,
                                             null_ratio=0.0)}, 50)
            .with_window("rn", F.row_number(), partition_by=[],
                         order_by=["v"]))


class TestFrames:
    """Bounded ROWS and RANGE frames vs the exact CPU oracle."""

    def _df(self, s, n=60):
        import numpy as np
        rng = np.random.default_rng(9)
        return s.create_dataframe({
            "g": rng.integers(0, 5, n).astype(np.int64),
            "o": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.integers(-50, 50, n).astype(np.int64),
        })

    def test_bounded_rows_frame(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s).with_window(
                "w", F.sum("v"), partition_by=["g"], order_by=["o"],
                frame=("rows", -2, 1)))

    def test_range_frame_sum(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s).with_window(
                "w", F.sum("v"), partition_by=["g"], order_by=["o"],
                frame=("range", -5, 5)))

    def test_range_frame_count_avg(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s)
            .with_window("c", F.count("v"), partition_by=["g"],
                         order_by=["o"], frame=("range", None, 0))
            .with_window("a", F.avg("v"), partition_by=["g"],
                         order_by=["o"], frame=("range", -3, 3)))

    def test_range_frame_desc(self):
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: self._df(s).with_window(
                "w", F.sum("v"), partition_by=["g"],
                order_by=[F.col("o").desc()], frame=("range", -4, 2)))

    def test_range_frame_with_null_order(self):
        from spark_rapids_tpu.api import functions as F

        def fn(s):
            df = s.create_dataframe({
                "g": [1, 1, 1, 1, 2, 2],
                "o": [1, None, 3, None, 2, 5],
                "v": [10, 20, 30, 40, 50, 60],
            })
            return df.with_window(
                "w", F.sum("v"), partition_by=["g"], order_by=["o"],
                frame=("range", -2, 2))
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_range_frame_half_unbounded_with_null_order(self):
        """UNBOUNDED sides reach the partition edge and take the
        null-order block in with them (Spark RANGE semantics)."""
        from spark_rapids_tpu.api import functions as F

        def fn(frame, order_desc=False):
            def run(s):
                df = s.create_dataframe({
                    "g": [1, 1, 1, 1, 2, 2],
                    "o": [1, None, 3, None, 2, 5],
                    "v": [10, 20, 30, 40, 50, 60],
                })
                ob = [F.col("o").desc()] if order_desc else ["o"]
                return df.with_window(
                    "w", F.sum("v"), partition_by=["g"], order_by=ob,
                    frame=frame)
            return run
        assert_tpu_and_cpu_are_equal_collect(fn(("range", None, 0)))
        assert_tpu_and_cpu_are_equal_collect(fn(("range", -1, None)))
        assert_tpu_and_cpu_are_equal_collect(
            fn(("range", None, 1), order_desc=True))
