"""Kernel-level tests: sort/group/join cores vs numpy oracles.

Pattern parity: reference unit suites like HashAggregatesSuite/CastOpSuite
compare GPU results against CPU Spark; here the oracle is numpy.
"""
import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu.columnar import Column, dtypes as T
from spark_rapids_tpu.kernels import canon, sort, aggregate, join, basic
from spark_rapids_tpu.kernels import strings as skern


def _col(vals, dtype=None):
    return Column.from_numpy(vals, dtype=dtype)


class TestCanon:
    def test_int_order(self):
        col = _col([5, -3, 0, None, 7], dtype=T.INT64)
        words = canon.column_key_words(col, 5)
        perm = np.asarray(sort.sort_permutation(words))[:5]
        got = [col.to_pylist(5)[i] for i in perm]
        assert got == [None, -3, 0, 5, 7]  # nulls first default

    def test_float_order_with_nan(self):
        col = _col(np.array([1.0, -np.inf, np.nan, -0.0, np.inf]))
        words = canon.column_key_words(col, 5)
        perm = np.asarray(sort.sort_permutation(words))[:5]
        vals = np.array([1.0, -np.inf, np.nan, -0.0, np.inf])[perm]
        assert vals[0] == -np.inf and np.isnan(vals[-1])  # NaN greatest

    def test_descending(self):
        col = _col([1, 3, 2], dtype=T.INT64)
        words = canon.column_key_words(col, 3, descending=True,
                                       nulls_last=True)
        perm = np.asarray(sort.sort_permutation(words))[:3]
        assert [[1, 3, 2][i] for i in perm] == [3, 2, 1]

    def test_string_order(self):
        vals = ["banana", "apple", None, "apricot", "b", ""]
        col = _col(vals, dtype=T.STRING)
        words = canon.column_key_words(col, 6)
        perm = np.asarray(sort.sort_permutation(words))[:6]
        got = [vals[i] for i in perm]
        assert got == [None, "", "apple", "apricot", "b", "banana"]

    def test_long_string_order(self):
        vals = ["x" * 30 + "a", "x" * 30 + "b", "x" * 9]
        col = _col(vals, dtype=T.STRING)
        words = canon.column_key_words(col, 3)
        perm = np.asarray(sort.sort_permutation(words))[:3]
        assert [vals[i] for i in perm] == ["x" * 9, "x" * 30 + "a",
                                           "x" * 30 + "b"]


class TestGroupBy:
    def test_sum_count(self):
        keys = _col([1, 2, 1, None, 2, 1], dtype=T.INT64)
        vals = _col([10.0, 20.0, 30.0, 40.0, None, 50.0], dtype=T.FLOAT64)
        words = canon.batch_key_words([keys], 6)
        plan = aggregate.groupby_plan(words)
        assert int(plan.num_groups) == 3  # null is its own group
        sums = np.asarray(aggregate.seg_sum(plan, vals.data, vals.validity))
        counts = np.asarray(aggregate.seg_count(plan, vals.validity))
        reps = np.asarray(plan.rep_indices)[:3]
        key_vals = [keys.to_pylist(6)[i] for i in reps]
        got = dict(zip(key_vals, zip(sums[:3], counts[:3])))
        assert got[None] == (40.0, 1)
        assert got[1] == (90.0, 3)
        assert got[2] == (20.0, 1)

    def test_min_max(self, rng):
        n = 500
        k = rng.integers(0, 20, n)
        v = rng.integers(-1000, 1000, n)
        keys = _col(k, dtype=T.INT64)
        vals = _col(v, dtype=T.INT64)
        words = canon.batch_key_words([keys], n)
        plan = aggregate.groupby_plan(words)
        g = int(plan.num_groups)
        mins = np.asarray(aggregate.seg_min(plan, vals.data, vals.validity))[:g]
        maxs = np.asarray(aggregate.seg_max(plan, vals.data, vals.validity))[:g]
        reps = np.asarray(plan.rep_indices)[:g]
        for i, r in enumerate(reps):
            kk = k[r]
            assert mins[i] == v[k == kk].min()
            assert maxs[i] == v[k == kk].max()

    def test_multi_key(self):
        k1 = _col([1, 1, 2, 2], dtype=T.INT64)
        k2 = _col(["a", "b", "a", "a"], dtype=T.STRING)
        words = canon.batch_key_words([k1, k2], 4)
        plan = aggregate.groupby_plan(words)
        assert int(plan.num_groups) == 3


class TestJoin:
    def test_inner_basic(self):
        bk = _col([1, 2, 2, 3], dtype=T.INT64)
        pk = _col([2, 4, 1, 2], dtype=T.INT64)
        bw = canon.batch_key_words([bk], 4)
        pw = canon.batch_key_words([pk], 4)
        bt = join.build(bw)
        jc = join.probe_counts(bt, pw, 4)
        counts = np.asarray(jc.counts)[:4]
        assert list(counts) == [2, 0, 1, 2]
        total = join.total_matches(jc.counts)
        assert total == 5
        p_idx, b_idx, live, tot = join.expand_matches(
            jc.lo, jc.counts, bt.perm, 8)
        pairs = sorted((int(p), int(bk.to_pylist(4)[b]))
                       for p, b, l in zip(p_idx, b_idx, live) if l)
        assert pairs == [(0, 2), (0, 2), (2, 1), (3, 2), (3, 2)]

    def test_null_keys_dont_match(self):
        bk = _col([1, None], dtype=T.INT64)
        pk = _col([None, 1], dtype=T.INT64)
        bt = join.build(canon.batch_key_words([bk], 2))
        jc = join.probe_counts(bt, canon.batch_key_words([pk], 2), 2)
        assert list(np.asarray(jc.counts)[:2]) == [0, 1]

    def test_null_safe_join(self):
        bk = _col([1, None], dtype=T.INT64)
        pk = _col([None, 1], dtype=T.INT64)
        bt = join.build(canon.batch_key_words([bk], 2))
        jc = join.probe_counts(bt, canon.batch_key_words([pk], 2), 2,
                               null_equals_null=True)
        assert list(np.asarray(jc.counts)[:2]) == [1, 1]

    def test_string_join(self):
        bk = _col(["x", "yy", "zzz"], dtype=T.STRING)
        pk = _col(["yy", "nope", "x"], dtype=T.STRING)
        # join requires identical word counts: build both against the
        # unified max width via shared canon call on equal-capacity cols
        bw = canon.batch_key_words([bk], 3)
        pw = canon.batch_key_words([pk], 3)
        assert len(bw) == len(pw)
        bt = join.build(bw)
        jc = join.probe_counts(bt, pw, 3)
        assert list(np.asarray(jc.counts)[:3]) == [1, 0, 1]

    def test_large_random_inner(self, rng):
        n, m = 300, 400
        bkv = rng.integers(0, 50, n)
        pkv = rng.integers(0, 60, m)
        bt = join.build(canon.batch_key_words([_col(bkv, dtype=T.INT64)], n))
        jc = join.probe_counts(
            bt, canon.batch_key_words([_col(pkv, dtype=T.INT64)], m), m)
        counts = np.asarray(jc.counts)[:m]
        expect = np.array([(bkv == x).sum() for x in pkv])
        assert (counts == expect).all()


class TestStrings:
    def test_upper_lower(self):
        col = _col(["Hello", "WORLD"], dtype=T.STRING)
        assert skern.upper(col).to_pylist(2) == ["HELLO", "WORLD"]
        assert skern.lower(col).to_pylist(2) == ["hello", "world"]

    def test_substring(self):
        col = _col(["hello", "ab", ""], dtype=T.STRING)
        out = skern.substring(col, 2, 3)
        assert out.to_pylist(3) == ["ell", "b", ""]

    def test_char_length_utf8(self):
        col = _col(["abc", "wörld", ""], dtype=T.STRING)
        lens = np.asarray(skern.char_length(col))[:3]
        assert list(lens) == [3, 5, 0]

    def test_contains_starts_ends(self):
        col = _col(["foobar", "barfoo", "baz"], dtype=T.STRING)
        assert list(np.asarray(skern.contains(col, b"foo"))[:3]) == [
            True, True, False]
        assert list(np.asarray(skern.starts_with(col, b"foo"))[:3]) == [
            True, False, False]
        assert list(np.asarray(skern.ends_with(col, b"foo"))[:3]) == [
            False, True, False]


class TestBasic:
    def test_compact_indices(self):
        mask = jnp.array([True, False, True, False, True, False, False, False])
        idx, cnt = basic.compact_indices(mask, 5)
        assert int(cnt) == 3
        assert list(np.asarray(idx))[:3] == [0, 2, 4]

    def test_hash_partition_stable(self):
        col = _col(np.arange(100), dtype=T.INT64)
        words = canon.value_words(col, 100)
        h = basic.hash_words(words)
        parts = np.asarray(basic.hash_to_partition(h, 8))
        assert parts.min() >= 0 and parts.max() < 8
        # deterministic
        h2 = basic.hash_words(canon.value_words(col, 100))
        assert (np.asarray(h) == np.asarray(h2)).all()


class TestTableGroupby:
    """Sort-free bucket-table group-by kernels (kernels/aggregate.py
    table_bucket/table_compact + pallas_ops.table_reduce)."""

    def test_table_bucket_single_key(self):
        import jax.numpy as jnp
        import numpy as np
        from spark_rapids_tpu.kernels import aggregate as agg_k
        k = jnp.asarray(np.array([5, 7, 5, 9, 7, 5], np.int64))
        w = (k.astype(jnp.int64).astype(jnp.uint64) ^
             jnp.uint64(1 << 63))
        valid = jnp.array([True, True, True, True, True, False])
        live = jnp.ones(6, bool)
        bucket, fit, mins, cards = agg_k.table_bucket(
            [w], [valid], live, 64)
        b = np.asarray(bucket)
        assert bool(fit)
        # same keys share buckets; invalid row gets the null digit 0
        assert b[0] == b[2] == b[5 - 5]
        assert b[1] == b[4]
        assert b[5] == 0  # null digit (valid=False, live=True)

    def test_table_bucket_overflow_sets_fit_false(self):
        import jax.numpy as jnp
        import numpy as np
        from spark_rapids_tpu.kernels import aggregate as agg_k
        k = jnp.asarray(np.array([0, 10**12], np.int64))
        w = (k.astype(jnp.uint64)) ^ jnp.uint64(1 << 63)
        valid = jnp.ones(2, bool)
        bucket, fit, _, _ = agg_k.table_bucket(
            [w], [valid], jnp.ones(2, bool), 64)
        assert not bool(fit)

    def test_table_reduce_scatter_and_compact(self):
        import jax.numpy as jnp
        import numpy as np
        from spark_rapids_tpu.kernels import aggregate as agg_k
        from spark_rapids_tpu.kernels.pallas_ops import table_reduce
        n, T = 4096, 64
        rng = np.random.default_rng(3)
        b = jnp.asarray(rng.integers(0, 10, n).astype(np.int32))
        v = jnp.asarray(rng.random(n).astype(np.float32))
        ones = jnp.ones(n, jnp.float32)
        sums, maxs = table_reduce(
            b, [ones, v], [jnp.where(v > 0, v, -jnp.inf)], T)
        ref_c = np.zeros(T)
        np.add.at(ref_c, np.asarray(b), 1.0)
        ref_s = np.zeros(T)
        np.add.at(ref_s, np.asarray(b), np.asarray(v, np.float64))
        ref_m = np.full(T, -np.inf)
        np.maximum.at(ref_m, np.asarray(b), np.asarray(v))
        assert np.allclose(np.asarray(sums[0]), ref_c)
        assert np.allclose(np.asarray(sums[1]), ref_s, rtol=1e-5)
        got_m = np.asarray(maxs[0])
        assert np.allclose(np.where(np.isfinite(got_m), got_m, -1),
                           np.where(np.isfinite(ref_m), ref_m, -1))
        present, order, ng = agg_k.table_compact(sums[0], T)
        assert int(ng) == 10
        assert np.array_equal(np.asarray(order)[:10], np.arange(10))

    def test_variable_float_agg_conf_off_matches_exact(self):
        import numpy as np
        from tests.harness import (assert_tpu_and_cpu_are_equal_collect)
        from spark_rapids_tpu.api import functions as F
        rng = np.random.default_rng(11)
        n = 5000
        data = {"k": rng.integers(0, 20, n).astype(np.int64),
                "x": rng.random(n)}

        def q(s):
            df = s.create_dataframe(data, num_partitions=2)
            return df.group_by("k").agg(F.sum("x").alias("sx"),
                                        F.min("x").alias("mn"))
        # exact mode: disable f32 accumulation -> bit-exact vs CPU
        assert_tpu_and_cpu_are_equal_collect(
            q, conf={"spark.rapids.tpu.sql.variableFloatAgg.enabled":
                     False})


class TestPairSuperaccumulator:
    """_seg_sum_f64_pair: the on-chip FLOAT64 sum path (f32-pair integer
    superaccumulator).  Called directly so the CPU test platform
    exercises the device code path."""

    def _run(self, vals, ks):
        import math
        import jax.numpy as jnp
        from spark_rapids_tpu.columnar import dtypes as T
        from spark_rapids_tpu.columnar.column import Column
        from spark_rapids_tpu.kernels import canon, aggregate as agg_k
        n = len(vals)
        kcol = Column(T.INT64, jnp.asarray(np.asarray(ks, np.int64)),
                      jnp.ones(n, bool))
        words = canon.batch_key_words([kcol], jnp.int32(n))
        plan = agg_k.groupby_plan(words)
        v = jnp.asarray(np.asarray(vals, np.float64))
        sv, sok = agg_k._sorted_vals(plan, v, jnp.ones(n, bool))
        got = np.asarray(agg_k._seg_sum_f64_pair(plan, sv, sok))
        for g, key in enumerate(np.unique(ks)):
            sel = np.asarray(vals)[np.asarray(ks) == key]
            if np.all(np.isfinite(sel)):
                expect = math.fsum(sel)
                # pair split keeps 48 bits/value; window keeps ~110 bits
                err = abs(got[g] - expect)
                bound = max(np.sum(np.abs(sel)) * 2.0 ** -46, 1e-300)
                assert err <= bound, (key, got[g], expect, err, bound)
            else:
                expect = np.sum(sel)
                assert (np.isnan(got[g]) and np.isnan(expect)) or \
                    got[g] == expect, (key, got[g], expect)

    def test_random_groups(self):
        rng = np.random.default_rng(7)
        self._run(rng.standard_normal(2000), rng.integers(0, 13, 2000))

    def test_wide_exponents(self):
        rng = np.random.default_rng(8)
        self._run(np.ldexp(rng.standard_normal(600),
                           rng.integers(-60, 60, 600)),
                  rng.integers(0, 5, 600))

    def test_specials_and_signs(self):
        self._run(np.array([1e30, 1.0, -1e30, np.inf, 3.0, np.nan,
                            2.0, -0.5, -0.25, -0.25, 0.0, -0.0]),
                  np.array([0, 0, 0, 1, 1, 2, 3, 3, 4, 4, 5, 5]))

    def test_cancellation_accuracy(self):
        # +x/-x pairs leave a small residue: the superaccumulator keeps
        # it exactly; pairwise f32-pair addition would lose it
        base = np.array([1e12, -1e12] * 500)
        resid = np.full(1000, 1e-3)
        self._run(base + resid, np.zeros(1000, np.int64))

    def test_group_isolation(self):
        # the window anchor is per GROUP: a 1e38 group must not push a
        # 1e-9 group's rows out of the accumulation window
        self._run(np.array([1e38, 1e-9, 1e-9, 3e37, 2e-9]),
                  np.array([0, 1, 1, 0, 1]))


class TestExactTableLanes:
    """Exact-float table-path lanes (fsum64/favg64/fminmax64): 8-bit
    chunk sums + two-stage u32 min/max, engaged when capacity >= table
    size.  Compared against the pyarrow oracle at tight tolerance."""

    def _q(self, data, conf=None):
        from tests.harness import assert_tpu_and_cpu_are_equal_collect
        from spark_rapids_tpu.api import functions as F

        def q(s):
            df = s.create_dataframe(data, num_partitions=2)
            return df.group_by("k").agg(
                F.sum("x").alias("sx"), F.avg("x").alias("ax"),
                F.min("x").alias("mn"), F.max("x").alias("mx"),
                F.count().alias("c"))
        assert_tpu_and_cpu_are_equal_collect(q, conf=conf or {})

    def test_exact_float_agg_table_path(self):
        rng = np.random.default_rng(3)
        n = 6000  # capacity 8192 >= table 4096: table path engages
        self._q({"k": rng.integers(0, 50, n).astype(np.int64),
                 "x": rng.standard_normal(n) * 1e6})

    def test_exact_float_agg_negatives_and_zeros(self):
        rng = np.random.default_rng(4)
        n = 5000
        x = rng.standard_normal(n)
        x[::17] = 0.0
        x[1::17] = -0.0
        self._q({"k": rng.integers(0, 20, n).astype(np.int64), "x": x})

    def test_exact_float_agg_specials(self):
        rng = np.random.default_rng(5)
        n = 5000
        x = rng.standard_normal(n)
        x[100] = np.inf
        x[200] = -np.inf
        x[300] = np.nan
        k = rng.integers(0, 8, n).astype(np.int64)
        # isolate specials per group so inf/nan semantics are exercised
        k[100], k[200], k[300] = 1, 2, 3
        self._q({"k": k, "x": x})

    def test_exact_float_agg_wide_spread_falls_back(self):
        # exponent spread > 2^63: the fit flag must route the batch to
        # the sort path and results stay correct
        rng = np.random.default_rng(6)
        n = 5000
        x = np.ldexp(rng.standard_normal(n), rng.integers(-80, 80, n))
        self._q({"k": rng.integers(0, 10, n).astype(np.int64), "x": x})

    def test_exact_float_agg_tiny_magnitudes(self):
        rng = np.random.default_rng(7)
        n = 5000
        x = rng.standard_normal(n) * 1e-30
        self._q({"k": rng.integers(0, 10, n).astype(np.int64), "x": x})
