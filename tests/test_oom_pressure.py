"""Reactive device-OOM handling: the real allocator's
RESOURCE_EXHAUSTED triggers spill-everything + retry
(DeviceMemoryEventHandler.onAllocFailure contract).  Simulated via
fault injection — a true HBM exhaustion on the shared tunnelled chip
would wedge the backend for every other test."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import (ColumnarBatch, Column, Schema,
                                       Field, dtypes as T)
from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
from spark_rapids_tpu.memory.pressure import is_device_oom, oom_retry
from spark_rapids_tpu.memory.spillable import SpillableBatch


class FakeXlaOom(RuntimeError):
    pass

FakeXlaOom.__name__ = "XlaRuntimeError"


def _batch(n=100):
    return ColumnarBatch(
        Schema([Field("a", T.INT64)]),
        [Column.from_numpy(list(range(n)), dtype=T.INT64)], n)


def test_is_device_oom_classifier():
    assert is_device_oom(FakeXlaOom(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 bytes"))
    assert is_device_oom(MemoryError("Failed to allocate device buffer"))
    assert not is_device_oom(ValueError("RESOURCE_EXHAUSTED"))
    assert not is_device_oom(FakeXlaOom("INVALID_ARGUMENT: bad shape"))


def test_oom_retry_spills_and_retries():
    cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
    sb = SpillableBatch(_batch())          # device-tier spill candidate
    calls = {"n": 0}

    def put():
        calls["n"] += 1
        if calls["n"] == 1:
            raise FakeXlaOom("RESOURCE_EXHAUSTED: Out of memory "
                             "allocating 16G")
        return "ok"

    assert oom_retry(put) == "ok"
    assert calls["n"] == 2
    # the retry spilled the device tier first
    assert cat._entries[sb.buffer_id].tier != StorageTier.DEVICE
    assert cat.oom_retries == 1
    sb.close()


def test_oom_retry_reraises_when_nothing_spillable():
    BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")

    def put():
        raise FakeXlaOom("RESOURCE_EXHAUSTED: Out of memory")
    with pytest.raises(FakeXlaOom):
        oom_retry(put)


def test_oom_retry_propagates_non_oom():
    BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")

    def bad():
        raise ValueError("not an oom")
    with pytest.raises(ValueError):
        oom_retry(bad)


def test_unspill_retries_after_injected_oom(monkeypatch):
    """acquire() of a spilled batch: first device put OOMs, the catalog
    spills the device tier and the retry materializes — WITHOUT the
    retry the injected error propagates and this test fails."""
    cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
    victim = SpillableBatch(_batch(500))    # will be spilled by retry
    sb = SpillableBatch(_batch(50))
    cat.spill_device_to_fit(cat.device_limit)   # push both to HOST
    assert cat._entries[sb.buffer_id].tier == StorageTier.HOST
    victim.materialize()                    # victim back on DEVICE
    assert cat._entries[victim.buffer_id].tier == StorageTier.DEVICE

    real = BufferCatalog._deserialize
    calls = {"n": 0}

    def flaky(self, payload):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FakeXlaOom("RESOURCE_EXHAUSTED: Out of memory "
                             "allocating 4.00G on device ordinal 0")
        return real(self, payload)
    monkeypatch.setattr(BufferCatalog, "_deserialize", flaky)
    got = sb.materialize()
    assert got.columns[0].to_pylist(50) == list(range(50))
    assert calls["n"] == 2
    # the retry pushed the device-resident victim down a tier
    assert cat._entries[victim.buffer_id].tier != StorageTier.DEVICE
    sb.close()
    victim.close()


def test_scan_ingest_retries_after_injected_oom(monkeypatch):
    """from_arrow (the scan-side device put) retries through the same
    contract."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar import arrow as A
    BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
    holder = SpillableBatch(_batch(200))
    t = pa.table({"x": list(range(64))})
    real = A.column_from_arrow
    calls = {"n": 0}

    def flaky(arr, capacity=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FakeXlaOom("RESOURCE_EXHAUSTED: Out of memory")
        return real(arr, capacity=capacity)
    monkeypatch.setattr(A, "column_from_arrow", flaky)
    b = A.from_arrow(t)
    assert b.columns[0].to_pylist(64) == list(range(64))
    assert calls["n"] == 2
    holder.close()
