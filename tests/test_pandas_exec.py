"""Pandas-exchange exec tests: mapInPandas / applyInPandas / grouped-agg
pandas UDFs on both engines.

Reference: the Python exec family (SURVEY.md §2.4/§2.8) —
GpuMapInPandasExec, GpuFlatMapGroupsInPandasExec, GpuAggregateInPandasExec.
"""
import numpy as np

from harness import (assert_tpu_and_cpu_are_equal_collect,
                     with_tpu_session)

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.udf import pandas_udf


def _df(s):
    rng = np.random.default_rng(5)
    n = 300
    return s.create_dataframe({
        "g": rng.integers(0, 8, n).astype(np.int64),
        "v": rng.integers(-50, 50, n).astype(np.int64),
        "x": np.round(rng.random(n), 4),
    }, num_partitions=3)


def test_map_in_pandas():
    def double_up(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["y"] = pdf["v"] * 2 + pdf["x"]
            yield pdf[["g", "y"]]

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).map_in_pandas(double_up, "g long, y double"))


def test_map_in_pandas_filtering():
    """The fn may change the row count (flat-map semantics)."""
    def keep_positive(it):
        for pdf in it:
            yield pdf[pdf["v"] > 0][["g", "v"]]

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).map_in_pandas(keep_positive, "g long, v long"))


def test_apply_in_pandas():
    def center(pdf):
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf[["g", "v"]]

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by("g").apply_in_pandas(
            center, "g long, v double"))


def test_apply_in_pandas_with_key():
    import pandas as pd

    def summarize(key, pdf):
        return pd.DataFrame({"g": [key[0]], "n": [len(pdf)],
                             "sv": [float(pdf["v"].sum())]})

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by("g").apply_in_pandas(
            summarize, "g long, n long, sv double"))


def test_grouped_agg_pandas_udf():
    mean_udf = pandas_udf(lambda v: float(v.mean()),
                          return_type=T.FLOAT64,
                          function_type="grouped_agg")
    wsum = pandas_udf(lambda v, x: float((v * x).sum()),
                      return_type=T.FLOAT64,
                      function_type="grouped_agg")
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by("g").agg(
            mean_udf("v").alias("mv"), wsum("v", "x").alias("wx")))


def test_map_in_pandas_runs_on_tpu_engine():
    def ident(it):
        yield from it

    def run(s):
        df = _df(s).map_in_pandas(ident, "g long, v long, x double")
        df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuMapInPandas" in tree, tree
        return []
    with_tpu_session(run)
