"""Pandas-exchange exec tests: mapInPandas / applyInPandas / grouped-agg
pandas UDFs on both engines.

Reference: the Python exec family (SURVEY.md §2.4/§2.8) —
GpuMapInPandasExec, GpuFlatMapGroupsInPandasExec, GpuAggregateInPandasExec.
"""
import numpy as np

from harness import (assert_tpu_and_cpu_are_equal_collect,
                     with_tpu_session)

from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.udf import pandas_udf


def _df(s):
    rng = np.random.default_rng(5)
    n = 300
    return s.create_dataframe({
        "g": rng.integers(0, 8, n).astype(np.int64),
        "v": rng.integers(-50, 50, n).astype(np.int64),
        "x": np.round(rng.random(n), 4),
    }, num_partitions=3)


def test_map_in_pandas():
    def double_up(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["y"] = pdf["v"] * 2 + pdf["x"]
            yield pdf[["g", "y"]]

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).map_in_pandas(double_up, "g long, y double"))


def test_map_in_pandas_filtering():
    """The fn may change the row count (flat-map semantics)."""
    def keep_positive(it):
        for pdf in it:
            yield pdf[pdf["v"] > 0][["g", "v"]]

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).map_in_pandas(keep_positive, "g long, v long"))


def test_apply_in_pandas():
    def center(pdf):
        pdf = pdf.copy()
        pdf["v"] = pdf["v"] - pdf["v"].mean()
        return pdf[["g", "v"]]

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by("g").apply_in_pandas(
            center, "g long, v double"))


def test_apply_in_pandas_with_key():
    import pandas as pd

    def summarize(key, pdf):
        return pd.DataFrame({"g": [key[0]], "n": [len(pdf)],
                             "sv": [float(pdf["v"].sum())]})

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by("g").apply_in_pandas(
            summarize, "g long, n long, sv double"))


def test_grouped_agg_pandas_udf():
    mean_udf = pandas_udf(lambda v: float(v.mean()),
                          return_type=T.FLOAT64,
                          function_type="grouped_agg")
    wsum = pandas_udf(lambda v, x: float((v * x).sum()),
                      return_type=T.FLOAT64,
                      function_type="grouped_agg")
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by("g").agg(
            mean_udf("v").alias("mv"), wsum("v", "x").alias("wx")))


def test_map_in_pandas_runs_on_tpu_engine():
    def ident(it):
        yield from it

    def run(s):
        df = _df(s).map_in_pandas(ident, "g long, v long, x double")
        df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuMapInPandas" in tree, tree
        return []
    with_tpu_session(run)


class TestCogroupedMapInPandas:
    """GpuFlatMapCoGroupsInPandasExec role: key-paired pandas groups."""

    def _dfs(self, s):
        import numpy as np
        left = s.create_dataframe({
            "k": np.array([1, 1, 2, 3], np.int64),
            "a": np.array([10.0, 20.0, 30.0, 40.0])})
        right = s.create_dataframe({
            "k": np.array([1, 2, 2, 4], np.int64),
            "b": np.array([1.0, 2.0, 3.0, 4.0])})
        return left, right

    def _q(self, s):
        left, right = self._dfs(s)

        def merge(lg, rg):
            import pandas as pd
            return pd.DataFrame({
                "k": [lg["k"].iloc[0] if len(lg) else rg["k"].iloc[0]],
                "suma": [float(lg["a"].sum())],
                "sumb": [float(rg["b"].sum())]})
        return (left.group_by("k")
                .cogroup(right.group_by("k"))
                .apply_in_pandas(merge, "k long, suma double, sumb double"))

    def test_matches_cpu(self):
        from harness import assert_tpu_and_cpu_are_equal_collect
        rows = sorted(assert_tpu_and_cpu_are_equal_collect(self._q))
        # keys 1,2 on both sides; 3 left-only; 4 right-only
        assert [r[0] for r in rows] == [1, 2, 3, 4]
        by_k = {r[0]: r for r in rows}
        assert by_k[1][1] == 30.0 and by_k[1][2] == 1.0
        assert by_k[3][1] == 40.0 and by_k[3][2] == 0.0
        assert by_k[4][1] == 0.0 and by_k[4][2] == 4.0

    def test_with_key_argument(self):
        from harness import with_tpu_session

        def q(s):
            left, right = self._dfs(s)

            def merge(key, lg, rg):
                import pandas as pd
                return pd.DataFrame({"k": [key[0]],
                                     "n": [len(lg) + len(rg)]})
            return (left.group_by("k")
                    .cogroup(right.group_by("k"))
                    .apply_in_pandas(merge, "k long, n long"))
        rows = sorted(with_tpu_session(lambda s: q(s).collect()))
        assert rows == [(1, 3), (2, 3), (3, 1), (4, 1)]


class TestWindowInPandas:
    """GpuWindowInPandasExec role: pandas agg over unbounded
    partitions, broadcast to every row."""

    def test_partition_mean_broadcast(self):
        from harness import assert_tpu_and_cpu_are_equal_collect

        def q(s):
            import numpy as np
            df = s.create_dataframe({
                "g": np.array([1, 1, 2, 2, 2], np.int64),
                "v": np.array([1.0, 3.0, 10.0, 20.0, 30.0])})

            def mean_of(v):
                return float(v.mean())
            return df.with_window_pandas("m", mean_of, ["v"], "double",
                                         partition_by=["g"])
        rows = sorted(assert_tpu_and_cpu_are_equal_collect(q))
        for g, v, m in rows:
            assert m == (2.0 if g == 1 else 20.0)


def test_window_pandas_global_partition():
    """Empty partition_by = one global unbounded window."""
    from harness import assert_tpu_and_cpu_are_equal_collect

    def q(s):
        import numpy as np
        df = s.create_dataframe({"v": np.array([1.0, 2.0, 3.0, 4.0])})

        def total(v):
            return float(v.sum())
        return df.with_window_pandas("t", total, ["v"], "double")
    rows = assert_tpu_and_cpu_are_equal_collect(q)
    assert all(r[1] == 10.0 for r in rows)
