"""Array/collection expression + explode tests, TPU vs CPU oracle.

Pattern parity: reference integration_tests/src/main/python/
collection_ops_test.py and generate_expr_test.py (explode/posexplode
with outer variants, arrays with nulls/empties).
"""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from harness import assert_tpu_and_cpu_are_equal_collect, with_tpu_session


LISTS = pa.table({
    "a": [1, 2, 3, 4, 5, 6],
    "l": [[1, 2, 2], None, [], [5, None, 3], [7], [None]],
    "sl": [["x", "yy"], None, [], ["b", None, "a"], ["zz"], [None]],
    "f": [[1.5, -2.0], [0.0], None, [3.25, None], [], [9.0]],
})


def _df(s):
    return s.create_dataframe(LISTS)


class TestCollectionOps:
    def test_size(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.size("l").alias("n"),
                                    F.size("sl").alias("ns")))

    def test_get_item(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.col("l").getItem(0).alias("x"),
                                    F.col("l").getItem(5).alias("oob"),
                                    F.col("sl")[1].alias("s1")))

    def test_get_item_dynamic_index(self):
        # getItem with a column index (ExtractValue must bind the key)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.col("l")[F.col("a") - 1].alias("x")))

    def test_element_at(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.element_at("l", 1).alias("e1"),
                F.element_at("l", -1).alias("em1"),
                F.element_at("sl", 2).alias("es"),
                F.element_at("l", 10).alias("oob")))

    def test_array_contains(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.array_contains("l", 2).alias("c2"),
                F.array_contains("sl", "a").alias("ca"),
                F.array_contains("f", 9.0).alias("cf")))

    def test_array_contains_column_needle(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.array_contains("l", F.col("a")).alias("c")))

    def test_array_contains_string_column_needle(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(pa.table({
                "sl": [["a", "b"], ["x"], None, ["yy", None]],
                "s": ["b", "nope", "a", "zz"]}))
            .select(F.array_contains("sl", F.col("s")).alias("c")))

    def test_array_contains_null_needle(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                F.array_contains("l", F.lit(None).cast("int")).alias("c"),
                F.array_contains("sl",
                                 F.lit(None).cast("string")).alias("cs")))

    def test_sort_array(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.sort_array("l").alias("asc"),
                F.sort_array("l", False).alias("desc"),
                F.sort_array("sl").alias("sasc"),
                F.sort_array("f", False).alias("fdesc")))

    def test_array_min_max(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.array_min("l").alias("mn"),
                F.array_max("l").alias("mx"),
                F.array_min("f").alias("fmn"),
                F.array_max("f").alias("fmx")))

    def test_create_array(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select(
                "a", F.array(F.col("a"), F.lit(7),
                             F.col("a") * 2).alias("arr")))

    def test_create_array_strings(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.create_dataframe(pa.table(
                {"s": ["a", None, "ccc"], "t": ["x", "yy", None]}))
            .select(F.array(F.col("s"), F.col("t")).alias("arr")))


class TestExplode:
    @pytest.mark.parametrize("c", ["l", "sl", "f"])
    def test_explode(self, c):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.explode(c).alias("x")))

    @pytest.mark.parametrize("c", ["l", "sl"])
    def test_explode_outer(self, c):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.explode_outer(c).alias("x")))

    def test_posexplode(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.posexplode("l").alias("x")))

    def test_posexplode_outer(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.posexplode_outer("sl").alias("x")))

    def test_explode_then_agg(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", F.explode("l").alias("x"))
            .group_by("x").agg(F.count("*").alias("n"),
                               F.sum("a").alias("sa")))

    def test_explode_runs_on_tpu(self):
        def fn(s):
            df = _df(s).select("a", F.explode("l").alias("x"))
            return df.collect()
        # test-mode conf asserts every node planned onto the TPU engine
        rows = with_tpu_session(
            fn, conf={"spark.rapids.tpu.sql.test.enabled": "true"})
        assert len(rows) == 8


class TestArrayFlow:
    """Array columns flowing through joins/sort/union/shuffle as payload."""

    def test_array_through_union(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", "l").union(
                _df(s).select("a", "l")))

    def test_array_through_sort(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", "l").order_by("a"),
            ignore_order=False)

    def test_array_through_join(self):
        def fn(s):
            left = _df(s).select("a", "l")
            right = _df(s).select(F.col("a").alias("b"))
            return left.join(right, left["a"] == right["b"], "inner") \
                .select("a", "l")
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_array_through_repartition(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _df(s).select("a", "l").repartition(3, "a"))

    def test_explode_of_created_array(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.range(0, 5).select(
                F.col("id"),
                F.explode(F.array(F.col("id"), F.col("id") * 10,
                                  F.lit(99))).alias("x")))



def _struct_df(s):
    import pyarrow as pa
    t = pa.table({
        "a": [1, 2, 3, 4],
        "st": pa.array([{"x": 1, "y": "u"}, None,
                        {"x": None, "y": "w"}, {"x": 4, "y": None}]),
        "mp": pa.array([{"k": 1, "j": 5}, None, {}, {"z": 9, "k": 2}],
                       type=pa.map_(pa.string(), pa.int64())),
    })
    return s.create_dataframe(t)


class TestStructs:
    def test_get_field(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                "a", F.col("st").getField("x").alias("sx"),
                F.col("st")["y"].alias("sy")))

    def test_create_struct(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                F.struct("a", (F.col("a") * 2).alias("b")).alias("s2")))

    def test_named_struct_roundtrip_field(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                F.named_struct("p", "a", "q", F.lit("z"))
                .getField("p").alias("p")))

    def test_struct_through_union_and_sort(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select("a", "st")
            .union(_struct_df(s).select("a", "st")).order_by("a"),
            ignore_order=False)


class TestMaps:
    def test_get_map_value(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                "a", F.col("mp")["k"].alias("mk"),
                F.element_at("mp", "z").alias("mz"),
                F.element_at("mp", "nope").alias("mn")))

    def test_map_keys_values_size(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                "a", F.map_keys("mp").alias("ks"),
                F.map_values("mp").alias("vs"),
                F.size("mp").alias("n")))

    def test_create_map(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                F.create_map(F.lit("one"), F.col("a"),
                             F.lit("two"), F.col("a") * 2).alias("m")))

    def test_map_through_shuffle(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select("a", "mp").repartition(3, "a"))

    def test_explode_map_keys(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: _struct_df(s).select(
                "a", F.explode(F.map_keys("mp")).alias("k")))


class TestDeviceCollect:
    """collect_list/collect_set on DEVICE (reference GpuCollectList/
    GpuCollectSet): lists assemble from the sort+segment plan's group
    contiguity; set dedupes via canonical value words.  Multi-partition
    plans shuffle LIST buffer batches between partial and final."""

    def _q(self, s, parts):
        import numpy as np
        from spark_rapids_tpu.api import functions as F
        rng = np.random.default_rng(5)
        df = s.create_dataframe({
            "k": rng.integers(0, 8, 300).astype(np.int64),
            "v": rng.integers(0, 6, 300).astype(np.int64)},
            num_partitions=parts)
        return (df.group_by("k")
                  .agg(F.collect_list("v").alias("cl"),
                       F.collect_set("v").alias("cs"),
                       F.count().alias("c")))

    def _check(self, parts):
        from harness import with_cpu_session, with_tpu_session
        cpu = {r[0]: r for r in with_cpu_session(
            lambda s: self._q(s, parts).collect())}
        tpu = {r[0]: r for r in with_tpu_session(
            lambda s: self._q(s, parts).collect())}
        assert set(cpu) == set(tpu)
        for k in cpu:
            assert sorted(cpu[k][1]) == sorted(tpu[k][1])
            assert sorted(cpu[k][2]) == sorted(tpu[k][2])
            assert cpu[k][3] == tpu[k][3]

    def test_single_partition(self):
        self._check(1)

    def test_multi_partition_through_shuffle(self):
        self._check(3)

    def test_stays_on_device(self):
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": True}))
        df = self._q(s, 2)
        df.collect()
        tree = df._last_physical_plan.tree_string()
        assert "TpuHashAggregate" in tree and "Cpu" not in tree, tree

    def test_collect_list_preserves_input_order(self):
        import numpy as np
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F

        def q(s):
            df = s.create_dataframe({
                "k": np.array([1, 1, 1, 2], np.int64),
                "v": np.array([30, 10, 20, 5], np.int64)})
            return df.group_by("k").agg(F.collect_list("v").alias("l"))
        rows = {r[0]: r[1] for r in with_tpu_session(
            lambda s: q(s).collect())}
        assert rows[1] == [30, 10, 20]
        assert rows[2] == [5]

    def test_collect_with_nulls_dropped(self):
        import pyarrow as pa
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F

        def q(s):
            df = s.create_dataframe(pa.table({
                "k": pa.array([1, 1, 1], pa.int64()),
                "v": pa.array([7, None, 7], pa.int64())}))
            return df.group_by("k").agg(
                F.collect_list("v").alias("l"),
                F.collect_set("v").alias("st"))
        rows = with_tpu_session(lambda s: q(s).collect())
        assert rows[0][1] == [7, 7]
        assert rows[0][2] == [7]

    def test_collect_set_strings_fall_back(self):
        from harness import with_tpu_session
        from spark_rapids_tpu.api import TpuSession, functions as F
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.enabled": True}))
        df = s.create_dataframe({"k": [1, 1], "v": ["a", "a"]})
        out = df.group_by("k").agg(F.collect_set("v").alias("st"))
        text = s.explain(out._plan)
        assert "Cpu" in text
        rows = out.collect()
        assert rows[0][1] == ["a"]
