"""CPU-vs-TPU equality harness.

Reference pattern (SURVEY.md §4): assert_gpu_and_cpu_are_equal_collect
(integration_tests asserts.py:340) runs the same DataFrame function under
CPU and GPU sessions by flipping spark.rapids.sql.enabled, then
deep-compares rows with float-ulp tolerance.  This is that harness for
the TPU build: the oracle engine is the pyarrow CPU path.
"""
import math

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf


def with_cpu_session(fn, conf=None):
    settings = {"spark.rapids.tpu.sql.enabled": False}
    settings.update(conf or {})
    s = TpuSession(TpuConf(settings))
    return fn(s)


def with_tpu_session(fn, conf=None):
    settings = {"spark.rapids.tpu.sql.enabled": True}
    settings.update(conf or {})
    s = TpuSession(TpuConf(settings))
    return fn(s)


def _normalize(v):
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        return v
    return v


def _row_key(row):
    return tuple(str(_normalize(v)) for v in row)


def canon_rows(rows):
    """Canonical multiset form of a result set: NaN-normalized rows in
    a None-safe total order.  For comparing engines on queries whose
    ORDER BY (if any) does not fully determine row order — the
    reference harness's ignore_order."""
    return sorted((tuple(_normalize(v) for v in r) for r in rows),
                  key=_row_key)


# Default float tolerance is ulp-level: variableFloatAgg defaults OFF
# (matching the reference's RapidsConf default), so the engines should
# agree to reassociation-level error.  Tests that opt into f32
# accumulation (variableFloatAgg=true in their conf) are compared at
# f32-level tolerance instead — keyed off the conf, so enabling the fast
# path in a test automatically selects the tolerance that matches it.
DEFAULT_FLOAT_REL = 1e-9
FAST_FLOAT_REL = 2e-5
_VFA_KEY = "spark.rapids.tpu.sql.variableFloatAgg.enabled"


def _rel_for_conf(conf):
    v = (conf or {}).get(_VFA_KEY, False)
    loose = v if isinstance(v, bool) else str(v).lower() == "true"
    return FAST_FLOAT_REL if loose else DEFAULT_FLOAT_REL


def _compare_rows(cpu_rows, tpu_rows, approx_float=True,
                  rel=DEFAULT_FLOAT_REL):
    assert len(cpu_rows) == len(tpu_rows), \
        f"row count: cpu={len(cpu_rows)} tpu={len(tpu_rows)}"
    for i, (cr, tr) in enumerate(zip(cpu_rows, tpu_rows)):
        assert len(cr) == len(tr), f"row {i} width differs"
        for j, (cv, tv) in enumerate(zip(cr, tr)):
            if isinstance(cv, float) and isinstance(tv, float):
                if math.isnan(cv) and math.isnan(tv):
                    continue
                if approx_float:
                    ok = (cv == tv or
                          abs(cv - tv) <= rel * max(abs(cv), abs(tv), 1.0))
                    assert ok, f"row {i} col {j}: cpu={cv!r} tpu={tv!r}"
                    continue
            assert cv == tv, f"row {i} col {j}: cpu={cv!r} tpu={tv!r}"


def assert_tpu_and_cpu_are_equal_collect(df_fn, conf=None, ignore_order=True,
                                         approx_float=True):
    """Run df_fn(session) on both engines and compare collected rows."""
    from spark_rapids_tpu.analysis import residency
    cpu_rows = with_cpu_session(lambda s: df_fn(s).collect(), conf)
    # The oracle collect is itself a declared d2h pull: the entire TPU
    # result set is materialized host-side for row comparison.  The
    # region is entered BEFORE the session snapshots its per-query
    # declared-transfer window, so oracle runs don't skew the
    # declared_transfer_sites exactness contract (test_residency.py).
    with residency.declared_transfer(site="oracle_compare"):
        tpu_rows = with_tpu_session(lambda s: df_fn(s).collect(), conf)
    if ignore_order:
        cpu_rows = sorted(cpu_rows, key=_row_key)
        tpu_rows = sorted(tpu_rows, key=_row_key)
    _compare_rows(cpu_rows, tpu_rows, approx_float=approx_float,
                  rel=_rel_for_conf(conf))
    return tpu_rows
