"""Soak-plane tests: the burn-rate/steady-state/leak-drift monitors
(obs/burn.py), the deterministic fault injector (service/faults.py),
the sustained-load harness (service/soak.py), the watchdog re-fire,
the anomaly false-positive accounting, the offline surfaces
(tools/report.py --soak, tools/history.py soak) and the lint scope
extension with its seeded fixture."""
import json
import os
from collections import deque

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F  # noqa: F401
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import anomaly, burn, history
from spark_rapids_tpu.service import faults as faults_mod
from spark_rapids_tpu.service import soak as soak_mod
from spark_rapids_tpu.service.faults import FaultInjector, build_schedule
from spark_rapids_tpu.service.soak import SoakConfig, run_soak


@pytest.fixture(autouse=True)
def _soak_reset():
    """Isolate the process-wide burn/history/anomaly planes and restore
    the default config afterwards (last-configured service wins)."""
    history.stop()
    history.reset()
    anomaly.reset()
    burn.reset()
    yield
    history.stop()
    default = TpuConf({})
    history.configure(default)
    anomaly.configure(default)
    burn.configure(default)
    history.reset()
    anomaly.reset()
    burn.reset()


def _row(i=0, tenant="tenant-a", queue_ms=1.0, exec_ms=20.0,
         outcome="completed", ts=None):
    return {"ts": 1000.0 + i if ts is None else ts, "tenant": tenant,
            "queue_ms": queue_ms, "exec_ms": exec_ms,
            "outcome": outcome}


# ---------------------------------------------------------------------------
# burn-rate windows
# ---------------------------------------------------------------------------

class TestBurnWindows:
    def test_window_rate_prunes_and_normalizes(self):
        win = deque([(0.0, 1), (5.0, 0), (9.0, 1), (10.0, 0)])
        # span 6s from ts=10 keeps [5, 9, 10]: 1 breach of 3, 1% budget
        rate = burn._window_rate(win, 10.0, 6.0, 0.01)
        assert rate == pytest.approx((1 / 3) / 0.01)
        assert [t for t, _ in win] == [5.0, 9.0, 10.0]

    def test_window_rate_empty_and_zero_budget(self):
        assert burn._window_rate(deque(), 10.0, 60.0, 0.01) == 0.0
        assert burn._window_rate(deque([(9.0, 1)]), 10.0, 60.0, 0.0) \
            == 0.0

    def test_fold_tracks_per_tenant_breaches(self, monkeypatch):
        from spark_rapids_tpu.obs import slo as _slo
        monkeypatch.setattr(_slo, "_TARGET_MS", 100.0)
        for i in range(8):
            burn.fold(_row(i=i, tenant="a", exec_ms=20.0))
        for i in range(8, 12):
            burn.fold(_row(i=i, tenant="b", exec_ms=500.0))
        rates = burn.burn_rates()
        assert rates["a"]["breaches"] == 0 and rates["a"]["count"] == 8
        assert rates["b"]["breaches"] == 4 and rates["b"]["count"] == 4
        assert rates["a"]["fast"] == 0.0
        # 100% breaching over a 1% budget burns at 100x
        assert rates["b"]["fast"] == pytest.approx(100.0)

    def test_failed_outcome_is_a_breach_regardless_of_latency(self,
                                                              monkeypatch):
        from spark_rapids_tpu.obs import slo as _slo
        monkeypatch.setattr(_slo, "_TARGET_MS", 1000.0)
        burn.fold(_row(exec_ms=1.0, outcome="failed"))
        assert burn.burn_rates()["tenant-a"]["breaches"] == 1

    def test_disabled_fold_is_a_noop(self):
        burn.configure(TpuConf({
            "spark.rapids.tpu.obs.burn.enabled": False}))
        burn.fold(_row())
        assert burn.stats_section()["folds"] == 0


# ---------------------------------------------------------------------------
# steady-state detector
# ---------------------------------------------------------------------------

class TestSteadyState:
    def test_convergence_loss_and_reconvergence(self):
        # constant latency converges after the configured streak...
        for i in range(10):
            burn.fold(_row(i=i, exec_ms=50.0))
        st = burn.steady_state()
        assert st["steady"] and st["converge_count"] == 1
        assert st["since_ts"] is not None
        # ...a fault-sized spike breaks it (one loss)...
        burn.fold(_row(i=10, exec_ms=2000.0))
        st = burn.steady_state()
        assert not st["steady"] and st["losses"] == 1
        assert st["streak"] == 0 and st["since_ts"] is None
        # ...and the detector re-converges afterwards (the EWMA decays
        # back from the spike at (1 - alpha) per fold, then the streak
        # has to rebuild from zero)
        for i in range(11, 45):
            burn.fold(_row(i=i, exec_ms=50.0))
        st = burn.steady_state()
        assert st["steady"] and st["converge_count"] == 2

    def test_non_completed_rows_never_move_the_ewma(self):
        for i in range(10):
            burn.fold(_row(i=i, exec_ms=50.0))
        ewma = burn.steady_state()["ewma_ms"]
        burn.fold(_row(i=10, exec_ms=9999.0, outcome="failed"))
        st = burn.steady_state()
        assert st["ewma_ms"] == ewma and st["steady"]


# ---------------------------------------------------------------------------
# leak drift
# ---------------------------------------------------------------------------

class TestLeakDrift:
    def _seed(self, samples):
        with burn._LOCK:
            burn._MEM_SAMPLES.clear()
            burn._MEM_SAMPLES.extend(samples)

    def test_clean_floor_is_exactly_zero(self):
        self._seed([4096, 8192, 4096, 9000, 4096, 4096])
        assert burn.leak_drift_bytes() == 0

    def test_creeping_floor_is_the_drift(self):
        self._seed([100, 100, 100, 228, 228, 228])
        assert burn.leak_drift_bytes() == 128

    def test_too_few_samples_and_shrinking_floor(self):
        self._seed([0, 10**9])
        assert burn.leak_drift_bytes() == 0
        self._seed([500, 500, 100, 100])
        assert burn.leak_drift_bytes() == 0

    def test_sample_memplane_appends_live_bytes(self):
        n0 = burn.stats_section()["leak"]["samples"]
        live = burn.sample_memplane()
        sec = burn.stats_section()["leak"]
        assert live >= 0 and sec["samples"] == n0 + 1

    def test_configure_resizes_sample_window(self):
        burn.configure(TpuConf({
            "spark.rapids.tpu.obs.burn.memSamples": 8}))
        self._seed(range(100))
        with burn._LOCK:
            assert burn._MEM_SAMPLES.maxlen == 8
            assert len(burn._MEM_SAMPLES) == 8


# ---------------------------------------------------------------------------
# fault injector
# ---------------------------------------------------------------------------

class _StubService:
    def __init__(self):
        self.events = []
        self.bundles = 0

        class _Ev:
            def __init__(ev):
                pass

            def log_service_event(ev, kind, query_id, **fields):
                self.events.append((kind, query_id, fields))
        self._events = _Ev()

    def _write_diag_bundle(self, trigger, handle, error):
        self.bundles += 1
        return f"/tmp/stub-bundle-{self.bundles}.json"


class TestFaultInjector:
    def test_build_schedule_is_seed_deterministic(self):
        a = build_schedule(7, 60.0)
        assert a == build_schedule(7, 60.0)
        assert a != build_schedule(8, 60.0)
        assert len(a) == len(faults_mod.FAULT_KINDS)
        assert sorted(k for _, k in a) == \
            sorted(faults_mod.FAULT_KINDS)
        # the middle 60% of the run, in firing order
        assert all(12.0 <= at <= 48.0 for at, _ in a)
        assert [at for at, _ in a] == sorted(at for at, _ in a)

    def test_build_schedule_count_wraps_kinds(self):
        sched = build_schedule(1, 10.0, kinds=("poison_query",), count=3)
        assert [k for _, k in sched] == ["poison_query"] * 3

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultInjector(_StubService(), [(1.0, "meteor_strike")])

    def test_poll_fires_marks_and_closes(self):
        svc = _StubService()
        inj = FaultInjector(svc, [(1.0, "poison_query")],
                            actions={"poison_query": lambda: 1},
                            guard_s=2.0)
        assert inj.poll(0.5) == [] and not inj.done()
        fired = inj.poll(1.2)
        assert len(fired) == 1 and inj.done()
        w = fired[0]
        assert w["kind"] == "poison_query" and w["detail"] == 1
        assert w["diag_bundle"] and inj.active() == ["poison_query"]
        begin = [e for e in svc.events if e[2]["phase"] == "begin"]
        assert begin and begin[0][0] == "fault"
        assert begin[0][2]["fault_kind"] == "poison_query"
        # guard passes: the window closes with an end marker
        inj.poll(3.5)
        assert w["end_s"] == 3.5 and inj.active() == []
        phases = [e[2]["phase"] for e in svc.events]
        assert phases == ["begin", "end"]
        end = svc.events[-1][2]
        assert end["end_s"] == 3.5
        assert end["diag_bundle"] == w["diag_bundle"]

    def test_action_error_is_contained(self):
        def _boom():
            raise RuntimeError("action exploded")
        inj = FaultInjector(_StubService(), [(0.0, "poison_query")],
                            actions={"poison_query": _boom})
        w = inj.poll(0.1)[0]
        assert "action exploded" in w["detail"]

    def test_close_all_ends_open_windows(self):
        inj = FaultInjector(_StubService(),
                            [(0.0, "forced_oom_storm")],
                            actions={"forced_oom_storm": lambda: 3})
        inj.poll(0.1)
        inj.close_all(0.5)
        assert inj.windows[0]["end_s"] == 0.5


# ---------------------------------------------------------------------------
# fault attribution math
# ---------------------------------------------------------------------------

def _window(at_s, end_s, guard=2.0):
    return {"id": "fault-1-kill_pipeline_worker",
            "kind": "kill_pipeline_worker", "at_s": at_s,
            "fired_s": at_s, "end_s": end_s, "detail": None,
            "diag_bundle": None, "p99_before_ms": None,
            "p99_during_ms": None, "p99_after_ms": None,
            "recovered": None, "recovery_s": None}


class TestFaultAttribution:
    def test_pctl_nearest_rank_and_empty(self):
        assert soak_mod._pctl([], 99) is None
        assert soak_mod._pctl([5.0], 99) == 5.0
        vals = [float(i) for i in range(1, 101)]
        # nearest-rank on 100 values: index round(q/100 * 99)
        assert soak_mod._pctl(vals, 50) == 51.0
        assert soak_mod._pctl(vals, 99) == 99.0

    def test_recovery_detected_after_spike(self):
        samples = [(t * 0.5, 30.0, "a", "s", True) for t in range(8)]
        samples += [(4.0 + t * 0.5, 500.0, "a", "s", True)
                    for t in range(4)]
        samples += [(6.0 + t * 0.5, 30.0, "a", "s", True)
                    for t in range(8)]
        w = _window(4.0, 6.0)
        soak_mod._attribute_faults([w], samples, 2.0)
        assert w["p99_before_ms"] == 30.0
        assert w["p99_during_ms"] == 500.0
        assert w["recovered"] and w["recovery_s"] == 4.0

    def test_never_recovering_spike(self):
        samples = [(t * 0.5, 30.0, "a", "s", True) for t in range(8)]
        samples += [(4.0 + t * 0.5, 900.0, "a", "s", True)
                    for t in range(10)]
        w = _window(4.0, 6.0)
        soak_mod._attribute_faults([w], samples, 2.0)
        assert w["recovered"] is False and w["recovery_s"] is None

    def test_no_prefault_traffic_counts_serving_as_recovery(self):
        samples = [(5.0, 30.0, "a", "s", True)]
        w = _window(1.0, 3.0)
        soak_mod._attribute_faults([w], samples, 2.0)
        assert w["p99_before_ms"] is None
        assert w["recovered"] and w["recovery_s"] == 2.0


# ---------------------------------------------------------------------------
# the harness, end to end (short, deterministic quotas)
# ---------------------------------------------------------------------------

class TestSoakRun:
    def test_clean_run_report_shape_and_totals(self, tmp_path):
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path)}))
        rep = run_soak(s, SoakConfig(
            duration_s=30.0, total_queries=12, qps=30.0, rows=64,
            partitions=2, seed=7, num_workers=2)).to_dict()
        tot = rep["totals"]
        assert tot["submitted"] == 12
        assert tot["completed"] + tot["failed"] == 12
        assert tot["failed"] == 0 and tot["sha_mismatch"] == 0
        assert rep["latency"]["p99_ms"] >= rep["latency"]["p50_ms"] > 0
        assert sum(rep["per_tenant"].values()) == tot["submitted"]
        assert sum(rep["per_shape"].values()) == tot["submitted"]
        assert rep["timeline"] and all(
            b["n"] >= 0 for b in rep["timeline"])
        assert rep["leak_drift_bytes"] == 0
        assert rep["fault_recovery_ratio"] == 1.0  # vacuous: no faults
        assert rep["burn"]["folds"] >= 12
        assert "steady" in rep and "service" in rep
        # the live section settles back to not-running
        sec = soak_mod.stats_section()
        assert sec["running"] is False
        assert sec["submitted"] == 12

    def test_fault_markers_on_event_log_and_flight(self, tmp_path):
        from spark_rapids_tpu.obs import flight as _flight
        from spark_rapids_tpu.tools.events import read_event_log
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.obs.history.dir":
                str(tmp_path / "hist")}))
        rep = run_soak(s, SoakConfig(
            duration_s=30.0, total_queries=10, qps=20.0, rows=64,
            partitions=2, seed=7, num_workers=2,
            faults=((0.05, "kill_pipeline_worker"),),
            fault_guard_s=0.2)).to_dict()
        assert rep["totals"]["failed"] == 0
        assert rep["totals"]["sha_mismatch"] == 0
        windows = rep["faults"]
        assert len(windows) == 1
        w = windows[0]
        assert w["kind"] == "kill_pipeline_worker"
        assert w["end_s"] is not None and w["recovered"] is not None
        marks = list(read_event_log(log, events="fault"))
        assert [(m["phase"], m["fault_kind"]) for m in marks] == \
            [("begin", "kill_pipeline_worker"),
             ("end", "kill_pipeline_worker")]
        assert all(m["query_id"] == w["id"] for m in marks)
        ev = [e for e in _flight.snapshot(query_id=w["id"])
              if e["kind"] == _flight.EV_FAULT]
        assert ev, "no EV_FAULT on the flight recorder"

    def test_monitors_add_zero_device_flushes(self, tmp_path):
        from spark_rapids_tpu.columnar import pending as _pending

        def _soak_flushes(conf_extra, sub):
            s = TpuSession(TpuConf({
                "spark.rapids.tpu.obs.history.dir":
                    str(tmp_path / sub), **conf_extra}))
            f0 = _pending.FLUSH_COUNT
            rep = run_soak(s, SoakConfig(
                duration_s=30.0, total_queries=8, qps=20.0, rows=64,
                partitions=2, seed=7, num_workers=2)).to_dict()
            assert rep["totals"]["failed"] == 0
            return _pending.FLUSH_COUNT - f0

        on = _soak_flushes({}, "on")
        off = _soak_flushes(
            {"spark.rapids.tpu.obs.burn.enabled": False}, "off")
        assert on == off, (on, off)

    def test_unknown_fault_kind_rejected_before_any_traffic(self):
        s = TpuSession(TpuConf({}))
        with pytest.raises(ValueError, match="unknown fault kind"):
            run_soak(s, SoakConfig(faults=((1.0, "nope"),)))


# ---------------------------------------------------------------------------
# anomaly false-positive accounting
# ---------------------------------------------------------------------------

def _sentinel_conf(minn=5, k=3, sigma=2.0):
    return TpuConf({
        "spark.rapids.tpu.obs.anomaly.warmupMinRuns": minn,
        "spark.rapids.tpu.obs.anomaly.breachRuns": k,
        "spark.rapids.tpu.obs.anomaly.sigma": sigma,
    })


def _hist_row(fp="fpA", exec_ms=100.0, i=0):
    return {"fingerprint": fp, "exec_ms": exec_ms, "queue_ms": 1.0,
            "host_drop_tax_ms": 0.0, "spill_ms": 0.0,
            "device_util_pct": 60.0, "flushes": 2,
            "doctor_cause": None, "ts": 1000.0 + i}


class TestAnomalyFpAccounting:
    def test_transient_breach_recovery_counts_one_fp(self):
        anomaly.configure(_sentinel_conf())
        for i in range(6):
            anomaly.fold(_hist_row(exec_ms=100.0, i=i))
        for i in range(6, 9):          # transient: breach...
            anomaly.fold(_hist_row(exec_ms=300.0, i=i))
        for i in range(9, 14):         # ...then full recovery
            anomaly.fold(_hist_row(exec_ms=100.0, i=i))
        sec = anomaly.stats_section()
        assert sec["breach_total"] == 1
        assert sec["fp_total"] == 1
        assert anomaly.fp_rate_pct() == 100.0

    def test_sustained_breach_is_not_a_false_positive(self):
        anomaly.configure(_sentinel_conf())
        for i in range(6):
            anomaly.fold(_hist_row(exec_ms=100.0, i=i))
        for i in range(6, 20):
            anomaly.fold(_hist_row(exec_ms=300.0, i=i))
        sec = anomaly.stats_section()
        assert sec["breach_total"] == 1 and sec["fp_total"] == 0
        assert anomaly.fp_rate_pct() == 0.0

    def test_no_breaches_reads_zero_rate(self):
        assert anomaly.fp_rate_pct() == 0.0


# ---------------------------------------------------------------------------
# watchdog re-fire
# ---------------------------------------------------------------------------

class _StubHandle:
    status = "RUNNING"
    _worker_ident = 0xdead


class TestWatchdogRefire:
    def _dog(self, monkeypatch, refire_s):
        from spark_rapids_tpu.obs import flight as _flight
        from spark_rapids_tpu.obs.watchdog import Watchdog
        svc = _StubService()
        svc._inflight_items = lambda: [("q-stall", _StubHandle())]
        monkeypatch.setattr(_flight, "thread_counts",
                            lambda: {0xdead: 5})
        return svc, Watchdog(svc, interval_s=0.1, stall_s=1.0,
                             refire_s=refire_s)

    def test_stalled_query_refires_at_rate_limit(self, monkeypatch):
        svc, dog = self._dog(monkeypatch, refire_s=2.0)
        t0 = 10**12
        assert dog.poll_once(now_ns=t0) == []       # baseline sample
        assert dog.poll_once(now_ns=t0 + int(1.5e9)) == ["q-stall"]
        # still stalled, but inside the re-fire window: silent
        assert dog.poll_once(now_ns=t0 + int(2.5e9)) == []
        # past the re-fire cadence: fires again with refire=1
        assert dog.poll_once(now_ns=t0 + int(3.6e9)) == ["q-stall"]
        refires = [f["refire"] for _, _, f in svc.events]
        assert refires == [0, 1]
        assert svc.bundles == 2
        assert dog.state()["refire_s"] == 2.0
        assert dog.state()["triggers"] == 2

    def test_refire_disabled_fires_once(self, monkeypatch):
        svc, dog = self._dog(monkeypatch, refire_s=0.0)
        t0 = 10**12
        dog.poll_once(now_ns=t0)
        assert dog.poll_once(now_ns=t0 + int(1.5e9)) == ["q-stall"]
        assert dog.poll_once(now_ns=t0 + int(9e9)) == []
        assert dog.state()["triggers"] == 1


# ---------------------------------------------------------------------------
# offline surfaces
# ---------------------------------------------------------------------------

def _mini_report():
    return {
        "config": {"duration_s": 5.0, "total_queries": 8, "qps": 4.0,
                   "rows": 64, "partitions": 2,
                   "tenants": ["a", "b"], "seed": 1,
                   "faults": [[1.0, "kill_pipeline_worker"]],
                   "fault_guard_s": 2.0, "bucket_s": 1.0,
                   "num_workers": 2},
        "totals": {"submitted": 8, "completed": 8, "failed": 0,
                   "shed": 0, "sha_mismatch": 0, "chaos_submitted": 0,
                   "chaos_failed": 0, "duration_s": 2.0,
                   "qps_actual": 4.0, "sustained_rows_s": 256.0},
        "latency": {"p50_ms": 20.0, "p95_ms": 30.0, "p99_ms": 40.0},
        "shed_rate_pct": 0.0,
        "per_tenant": {"a": 4, "b": 4},
        "per_shape": {"hot_agg": 8},
        "timeline": [
            {"t_s": 0.0, "n": 4, "qps": 4.0, "p50_ms": 20.0,
             "p99_ms": 25.0, "failed": 0, "shed": 0, "faults": []},
            {"t_s": 1.0, "n": 4, "qps": 4.0, "p50_ms": 22.0,
             "p99_ms": 80.0, "failed": 0, "shed": 0,
             "faults": ["kill_pipeline_worker"]}],
        "burn": {"tenants": {"a": {"fast": 0.0, "slow": 0.0,
                                   "count": 4, "breaches": 0},
                             "b": {"fast": 2.5, "slow": 1.0,
                                   "count": 4, "breaches": 1}}},
        "steady": {"steady": True, "streak": 9, "ewma_ms": 21.0,
                   "slope_pct": 0.3, "converge_count": 1, "losses": 0,
                   "since_ts": 123.0},
        "leak_drift_bytes": 0,
        "anomaly": {"breach_total": 0, "fp_total": 0,
                    "fp_rate_pct": 0.0},
        "faults": [{"id": "fault-1-kill_pipeline_worker",
                    "kind": "kill_pipeline_worker", "at_s": 1.0,
                    "fired_s": 1.0, "end_s": 3.0, "detail": 1,
                    "diag_bundle": "/tmp/x.json",
                    "p99_before_ms": 25.0, "p99_during_ms": 80.0,
                    "p99_after_ms": 26.0, "recovered": True,
                    "recovery_s": 4.0}],
        "fault_recovery_ratio": 1.0,
        "service": {"slo": {}, "scheduler": {}, "history": {}},
    }


class TestSoakSurfaces:
    def test_render_soak_report_carries_the_story(self):
        from spark_rapids_tpu.tools.report import render_soak_report
        text = render_soak_report(_mini_report())
        assert "soak run" in text
        assert "kill_pipeline_worker" in text
        assert "steady" in text and "leak_drift_bytes=0" in text
        assert "[!! budget]" in text      # tenant b burns >= 1.0
        assert "fault_recovery_ratio=1.0" in text
        assert "bundle=/tmp/x.json" in text

    def test_report_main_soak_flag(self, tmp_path, capsys):
        from spark_rapids_tpu.tools.report import main as report_main
        p = tmp_path / "soak.json"
        p.write_text(json.dumps(_mini_report()))
        assert report_main([str(p), "--soak"]) == 0
        out = capsys.readouterr().out
        assert "fault windows" in out

    def test_history_soak_windows_math(self):
        rows = [{"ts": 100.0 + i, "queue_ms": 1.0,
                 "exec_ms": 20.0 if i < 20 else 200.0,
                 "outcome": "completed"} for i in range(40)]
        from spark_rapids_tpu.tools.history import soak_windows
        wins = soak_windows(rows, buckets=4)
        assert len(wins) == 4
        assert sum(w["n"] for w in wins) == 40
        assert wins[0]["p99_ms"] == pytest.approx(21.0)
        assert wins[-1]["p99_ms"] == pytest.approx(201.0)
        assert all(w["qps"] > 0 for w in wins)
        assert wins[0]["outcomes"] == {"completed": 10}

    def test_history_soak_cli_empty_dir(self, tmp_path):
        from spark_rapids_tpu.tools.history import main as history_main
        assert history_main(["soak", str(tmp_path)]) == 1

    def test_stats_section_shapes(self):
        sec = burn.stats_section()
        assert {"enabled", "folds", "tenants", "steady", "leak",
                "history_write_p99_us"} <= set(sec)
        live = soak_mod.stats_section()
        assert {"running", "qps_target", "submitted", "completed",
                "active_faults"} <= set(live)


# ---------------------------------------------------------------------------
# lint scope extension + seeded fixture
# ---------------------------------------------------------------------------

class TestSoakLint:
    MODULES = ("spark_rapids_tpu/obs/burn.py",
               "spark_rapids_tpu/service/soak.py",
               "spark_rapids_tpu/service/faults.py")

    def test_soak_modules_in_sync_obs_hyg_scopes(self):
        from spark_rapids_tpu.analysis import lint as AL
        for rel in self.MODULES:
            scopes = AL._scopes_for(rel)
            assert AL.SYNC001 in scopes, rel
            assert AL.OBS002 in scopes, rel
            assert AL.HYG002 in scopes, rel

    def test_seeded_soak_fixture_trips_all_three_rules(self):
        from spark_rapids_tpu.analysis import lint as AL
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "lint_fixtures", "soak_sync.py")
        with open(path) as f:
            fs = AL.lint_source(f.read(), path)
        rules = {f.rule for f in fs}
        assert {AL.SYNC001, AL.OBS002, AL.HYG002} <= rules

    def test_shipped_soak_modules_lint_clean(self):
        from spark_rapids_tpu.analysis import lint as AL
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in self.MODULES:
            path = os.path.join(repo, rel)
            with open(path) as f:
                fs = AL.lint_source(f.read(), rel,
                                    scopes=AL._scopes_for(rel))
            assert fs == [], (rel, AL.format_findings(fs))


# ---------------------------------------------------------------------------
# the long one: a seeded three-kind chaos schedule
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosSoakSlow:
    def test_seeded_chaos_schedule_correct_and_correlated(self, tmp_path):
        from spark_rapids_tpu.tools.events import read_event_log
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.obs.history.dir":
                str(tmp_path / "hist")}))
        sched = build_schedule(42, 12.0)
        rep = run_soak(s, SoakConfig(
            duration_s=12.0, qps=8.0, rows=256, partitions=2,
            seed=42, num_workers=2, faults=sched,
            fault_guard_s=1.0)).to_dict()
        tot = rep["totals"]
        # the workload never fails or mis-hashes; the chaos tenant's
        # intentional failures are accounted separately
        assert tot["failed"] == 0 and tot["sha_mismatch"] == 0
        assert tot["chaos_submitted"] >= 4      # poison + OOM burst
        assert tot["chaos_failed"] == tot["chaos_submitted"]
        windows = rep["faults"]
        assert sorted(w["kind"] for w in windows) == \
            sorted(faults_mod.FAULT_KINDS)
        # every window closed and carries its measured p99 attribution
        assert all(w["end_s"] is not None for w in windows)
        assert all(w["p99_before_ms"] is not None for w in windows)
        assert rep["fault_recovery_ratio"] >= 2.0 / 3.0
        assert rep["leak_drift_bytes"] == 0
        # the detector converged at least once and the event log saw a
        # begin AND an end marker per fault kind
        assert rep["steady"]["converge_count"] >= 1
        marks = list(read_event_log(log, events="fault"))
        for kind in faults_mod.FAULT_KINDS:
            assert ("begin", kind) in [(m["phase"], m["fault_kind"])
                                       for m in marks]
            assert ("end", kind) in [(m["phase"], m["fault_kind"])
                                     for m in marks]
