"""Memory tiers + shuffle unit tests.

Reference pattern (SURVEY.md §4.2): RapidsBufferCatalogSuite,
RapidsDeviceMemoryStoreSuite, RapidsDiskStoreSuite, GpuPartitioningSuite,
and the mock-transport shuffle suites (RapidsShuffleClientSuite etc.) —
distributed logic tested without real hardware by injecting transports.
"""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import ColumnarBatch, dtypes as T
from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.shuffle.manager import (ShuffleManager, ShuffleCatalog,
                                              ShuffleBlockId, LocalTransport,
                                              ShuffleTransport)
from spark_rapids_tpu.shuffle.partitioners import (HashPartitioner,
                                                   RoundRobinPartitioner,
                                                   SinglePartitioner,
                                                   RangePartitioner)
from spark_rapids_tpu.expr import core as ec
from spark_rapids_tpu.plan.logical import SortOrder


def _batch(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch.from_pydict({
        "k": [int(x) for x in rng.integers(0, 10, n)],
        "v": [float(x) for x in rng.random(n)],
        "s": [f"s{int(x)}" for x in rng.integers(0, 5, n)],
    })


class TestBufferCatalog:
    def test_register_acquire_roundtrip(self):
        cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
        b = _batch()
        sb = SpillableBatch(b, catalog=cat)
        got = sb.materialize()
        assert got.to_pydict() == b.to_pydict()
        sb.close()
        assert cat.stats()["num_buffers"] == 0

    def test_spill_to_host_and_back(self):
        cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
        b = _batch()
        sb = SpillableBatch(b, catalog=cat)
        spilled = cat.spill_device_to_fit(cat.device_limit)  # force all out
        assert spilled > 0
        assert cat.device_bytes == 0
        e = cat._entries[sb.buffer_id]
        assert e.tier == StorageTier.HOST
        got = sb.materialize()  # unspill
        assert got.to_pydict() == b.to_pydict()
        assert cat._entries[sb.buffer_id].tier == StorageTier.DEVICE
        sb.close()

    def test_spill_cascade_to_disk(self):
        cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill",
                                  host_limit=1)  # force host overflow
        b = _batch()
        sb = SpillableBatch(b, catalog=cat)
        cat.spill_device_to_fit(cat.device_limit)
        e = cat._entries[sb.buffer_id]
        assert e.tier == StorageTier.DISK
        assert e.disk_path is not None
        got = sb.materialize()
        assert got.to_pydict() == b.to_pydict()
        sb.close()

    def test_spill_priority_order(self):
        cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
        low = SpillableBatch(_batch(seed=1), priority=-100, catalog=cat)
        high = SpillableBatch(_batch(seed=2), priority=100, catalog=cat)
        # spill just enough for one buffer: lowest priority goes first
        cat.device_limit = cat.device_bytes  # full
        cat.spill_device_to_fit(low.nbytes)
        assert cat._entries[low.buffer_id].tier == StorageTier.HOST
        assert cat._entries[high.buffer_id].tier == StorageTier.DEVICE
        low.close()
        high.close()


class TestPartitioners:
    def test_hash_partitioner_split(self):
        b = _batch(200)
        p = HashPartitioner([ec.AttributeReference("k", T.INT64)], 4)
        split = p.split(b)
        total = 0
        seen = []
        for pid in range(4):
            piece = split.partition_slice(pid)
            if piece is None:
                continue
            total += piece.num_rows
            seen.extend(piece.to_pydict()["k"])
        assert total == 200
        # determinism: same keys land in same partition
        split2 = p.split(b)
        assert (split2.offsets == split.offsets).all()

    def test_round_robin_balanced(self):
        b = _batch(100)
        p = RoundRobinPartitioner(4)
        split = p.split(b)
        sizes = [split.offsets[i + 1] - split.offsets[i] for i in range(4)]
        assert max(sizes) - min(sizes) <= 1

    def test_single(self):
        b = _batch(50)
        p = SinglePartitioner()
        split = p.split(b)
        assert split.partition_slice(0).num_rows == 50

    def test_range_partitioner_ordering(self):
        b = _batch(400, seed=3)
        orders = [SortOrder(ec.AttributeReference("v", T.FLOAT64))]
        p = RangePartitioner(orders, 4)
        p.fit([b])
        split = p.split(b)
        highs = []
        for pid in range(4):
            piece = split.partition_slice(pid)
            if piece is None:
                continue
            vs = [v for v in piece.to_pydict()["v"] if v is not None]
            if vs:
                if highs:
                    assert min(vs) >= max(highs)  # ranges are ordered
                highs = vs
        assert sum(split.offsets[i + 1] - split.offsets[i]
                   for i in range(4)) == 400


class RecordingTransport(ShuffleTransport):
    """Mock transport (the Mockito-mock pattern from the reference's

    RapidsShuffleTestHelper)."""

    def __init__(self, catalog):
        self.catalog = catalog
        self.fetched = []

    def fetch(self, blocks):
        self.fetched.extend(blocks)
        for b in blocks:
            yield from self.catalog.get(b)


class TestShuffleManager:
    def test_write_read_partition(self):
        BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
        mgr = ShuffleManager()
        sid = mgr.new_shuffle_id()
        b0, b1 = _batch(30, seed=4), _batch(20, seed=5)
        mgr.write_map_output(sid, 0, {0: [b0]})
        mgr.write_map_output(sid, 1, {0: [b1], 1: [b0]})
        got0 = list(mgr.read_partition(sid, 0))
        assert sum(b.num_rows for b in got0) == 50
        got1 = list(mgr.read_partition(sid, 1))
        assert sum(b.num_rows for b in got1) == 30
        mgr.cleanup(sid)
        assert mgr.catalog.blocks_for_reduce(sid, 0) == []

    def test_transport_spi_injection(self):
        BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
        mgr = ShuffleManager()
        rec = RecordingTransport(mgr.catalog)
        mgr.transport = rec
        sid = mgr.new_shuffle_id()
        mgr.write_map_output(sid, 0, {2: [_batch(10, seed=6)]})
        out = list(mgr.read_partition(sid, 2))
        assert sum(b.num_rows for b in out) == 10
        assert rec.fetched == [ShuffleBlockId(sid, 0, 2)]

    def test_shuffle_data_survives_spill(self):
        cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
        mgr = ShuffleManager()
        sid = mgr.new_shuffle_id()
        b = _batch(40, seed=7)
        expect = b.to_pydict()
        mgr.write_map_output(sid, 0, {0: [b]})
        cat.spill_device_to_fit(cat.device_limit)  # push everything out
        got = list(mgr.read_partition(sid, 0))
        assert got[0].to_pydict() == expect


class TestNativeBlockCodec:
    """Native C++ LZ codec (nvcomp role, SURVEY §2.10 item 4)."""

    def test_roundtrip_patterns(self):
        import numpy as np
        from spark_rapids_tpu.native import tplz_compress, tplz_decompress
        rng = np.random.default_rng(5)
        cases = [
            b"",
            b"x",
            b"ab" * 10_000,
            rng.integers(0, 50, 100_000).astype(np.int64).tobytes(),
            rng.integers(0, 2**63, 5_000).astype(np.int64).tobytes(),
        ]
        for data in cases:
            c = tplz_compress(data)
            assert tplz_decompress(c, len(data)) == data

    def test_codec_spi(self):
        from spark_rapids_tpu.shuffle.compression import get_codec
        codec = get_codec("tplz")
        data = b"hello shuffle world " * 1000
        c = codec.compress(data)
        assert len(c) < len(data) // 10
        assert codec.decompress(c, len(data)) == data

    def test_corrupt_input_raises(self):
        import pytest
        from spark_rapids_tpu.native import tplz_decompress
        with pytest.raises(RuntimeError):
            tplz_decompress(b"\xff\xff\xff\xff\x10\x20", 1000)
