"""Columnar substrate round-trip tests (SURVEY.md §7 build stage 1 oracle)."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import (ColumnarBatch, Column, Schema, Field,
                                       concat_batches, dtypes as T)


def test_int_column_roundtrip():
    col = Column.from_numpy([1, 2, None, 4], dtype=T.INT64)
    assert col.capacity == 16
    assert col.to_pylist(4) == [1, 2, None, 4]


def test_float_column_roundtrip():
    col = Column.from_numpy(np.array([1.5, -2.5, 3.25]))
    assert col.dtype == T.FLOAT64
    assert col.to_pylist(3) == [1.5, -2.5, 3.25]


def test_string_column_roundtrip():
    vals = ["hello", None, "", "wörld", "a" * 40]
    col = Column.from_numpy(vals, dtype=T.STRING)
    assert col.to_pylist(5) == vals


def test_bucket_capacity_powers_of_two():
    from spark_rapids_tpu.columnar import bucket_capacity
    assert bucket_capacity(0) == 16
    assert bucket_capacity(16) == 16
    assert bucket_capacity(17) == 32
    assert bucket_capacity(1000) == 1024


def test_batch_from_pydict_roundtrip():
    b = ColumnarBatch.from_pydict({
        "a": [1, 2, 3], "b": [1.0, None, 3.0], "s": ["x", "y", None]})
    assert b.num_rows == 3
    assert b.to_pydict() == {
        "a": [1, 2, 3], "b": [1.0, None, 3.0], "s": ["x", "y", None]}


def test_batch_select_and_with_column():
    b = ColumnarBatch.from_pydict({"a": [1, 2], "b": [3, 4]})
    s = b.select(["b"])
    assert s.to_pydict() == {"b": [3, 4]}
    c = Column.from_numpy([9, 9], capacity=b.capacity)
    b2 = b.with_column("c", c)
    assert b2.to_pydict()["c"] == [9, 9]


def test_concat_batches_mixed():
    b1 = ColumnarBatch.from_pydict({"a": [1, None], "s": ["p", "q"]})
    b2 = ColumnarBatch.from_pydict({"a": [3], "s": [None]}, schema=b1.schema)
    out = concat_batches([b1, b2])
    assert out.num_rows == 3
    assert out.to_pydict() == {"a": [1, None, 3], "s": ["p", "q", None]}


def test_gather_strings():
    import jax.numpy as jnp
    col = Column.from_numpy(["aa", "b", None, "cccc"], dtype=T.STRING)
    g = col.gather(jnp.array([3, 0, 0, 1]))
    assert g.to_pylist(4) == ["cccc", "aa", "aa", "b"]


def test_slice():
    b = ColumnarBatch.from_pydict({"a": list(range(10))})
    s = b.slice(3, 4)
    assert s.to_pydict() == {"a": [3, 4, 5, 6]}


def test_decimal_dtype():
    d = T.DecimalType(12, 2)
    assert d.name == "decimal(12,2)"
    with pytest.raises(ValueError):
        T.DecimalType(25, 2)


def test_large_min_capacity_padding():
    """Production runs with a 1024-row minimum bucket
    (SPARK_RAPIDS_TPU_MIN_CAPACITY); exercise a large pad ratio
    explicitly since the suite pins the bucket to 16."""
    col = Column.from_numpy([1, 2, None, 4], dtype=T.INT64, capacity=1024)
    assert col.capacity == 1024
    assert col.to_pylist(4) == [1, 2, None, 4]
    from spark_rapids_tpu.columnar.column import StringColumn
    sc = StringColumn.from_pylist(["ab", None, "c" * 40], capacity=1024)
    assert sc.capacity == 1024
    assert sc.max_bytes == 40
    assert sc.to_pylist(3) == ["ab", None, "c" * 40]
