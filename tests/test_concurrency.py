"""Concurrency: scan prefetch overlap + DeviceSemaphore admission.

Reference: GpuSemaphore.scala:27,101 (bounded concurrent device tasks)
and the multithreaded cloud reader (scan I/O decoupled from device
compute).  The prefetch path must produce IDENTICAL rows to the
sequential path, the semaphore must actually gate admissions, and
producer threads must run ahead of consumption.
"""
import os
import threading
import time

import numpy as np
import pytest

from harness import with_cpu_session, with_tpu_session

from spark_rapids_tpu.memory.arena import DeviceSemaphore


@pytest.fixture(scope="module")
def parquet_dir(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as papq
    d = tmp_path_factory.mktemp("scan_prefetch")
    rng = np.random.default_rng(3)
    for i in range(6):
        t = pa.table({
            "k": rng.integers(0, 40, 5000).astype(np.int64),
            "v": rng.standard_normal(5000)})
        papq.write_table(t, os.path.join(str(d), f"part{i}.parquet"))
    return str(d)


class TestDeviceSemaphore:
    def test_bounds_concurrent_holders(self):
        sem = DeviceSemaphore(2)
        active = []
        peak = []
        lock = threading.Lock()

        def task():
            sem.acquire_if_necessary()
            try:
                with lock:
                    active.append(1)
                    peak.append(len(active))
                time.sleep(0.02)
                with lock:
                    active.pop()
            finally:
                sem.release()
        ts = [threading.Thread(target=task) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert max(peak) <= 2
        assert len(peak) == 8   # everyone eventually ran

    def test_reentrant_same_thread(self):
        sem = DeviceSemaphore(1)
        sem.acquire_if_necessary()
        sem.acquire_if_necessary()   # same thread: no deadlock
        sem.release()
        sem.release()
        # fully released: another thread can acquire
        ok = []

        def probe():
            sem.acquire_if_necessary()
            ok.append(True)
            sem.release()
        t = threading.Thread(target=probe)
        t.start()
        t.join(timeout=5)
        assert ok == [True]


class TestScanPrefetch:
    def _q(self, s, path):
        from spark_rapids_tpu.api import functions as F
        return (s.read.parquet(path)
                 .filter(F.col("v") > -2.0)
                 .group_by("k")
                 .agg(F.sum("v").alias("sv"), F.count().alias("c")))

    def test_prefetch_rows_identical(self, parquet_dir):
        nc = {"spark.rapids.tpu.io.deviceScanCache.enabled": False}
        on = {"spark.rapids.tpu.sql.reader.prefetch.enabled": True, **nc}
        off = {"spark.rapids.tpu.sql.reader.prefetch.enabled": False, **nc}
        r_on = sorted(with_tpu_session(
            lambda s: self._q(s, parquet_dir).collect(), on))
        r_off = sorted(with_tpu_session(
            lambda s: self._q(s, parquet_dir).collect(), off))
        r_cpu = sorted(with_cpu_session(
            lambda s: self._q(s, parquet_dir).collect()))
        assert len(r_on) == len(r_cpu) == 40
        for a, b, c in zip(r_on, r_off, r_cpu):
            assert a[0] == b[0] == c[0]
            assert abs(a[1] - c[1]) < 1e-6 and abs(b[1] - c[1]) < 1e-6
            assert a[2] == b[2] == c[2]

    def test_producers_run_ahead(self, parquet_dir):
        """Producer threads decode ahead: by the time the FIRST batch is
        consumed, prefetch threads exist and other partitions' queues
        already hold data."""
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.sql.enabled": True,
            "spark.rapids.tpu.sql.reader.prefetch.enabled": True,
            # this test asserts on the prefetch machinery itself: a
            # device-cache replay (no reader threads) must not satisfy it
            "spark.rapids.tpu.io.deviceScanCache.enabled": False}))
        df = s.read.parquet(parquet_dir)
        phys = s._plan(df._plan)
        scan = phys
        while scan.children:
            scan = scan.children[0]
        parts = scan.execute()
        assert len(parts) > 1
        first = next(iter(parts[0]))
        assert first.num_rows > 0
        # the remaining partitions' producer THREADS exist already —
        # started eagerly at execute(), decoding while partition 0
        # computes; without prefetch no such thread would ever run
        deadline = time.time() + 10
        names = []
        while time.time() < deadline:
            names = [t.name for t in threading.enumerate()
                     if t.name == "tpu-scan-prefetch"]
            if names:
                break
            time.sleep(0.01)
        assert names, "no prefetch producer threads observed"
        got_rows = sum(b.num_rows for p in parts[1:] for b in p)
        assert got_rows > 0
