"""TPC-DS under shuffle.mode=mesh on the virtual 8-device mesh.

The round-4 verdict asked for the mesh path to be EXERCISED by real
queries, not just unit tests: this runs a TPC-DS subset with the mesh
conf on (aggregates/joins/sorts whose shapes qualify run as shard_map
SPMD programs over lax.all_to_all; everything else falls back to the
in-process execs) and verifies row equality against the CPU oracle.
"""
import math
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import tpcds  # noqa: E402

from harness import with_cpu_session, with_tpu_session  # noqa: E402

MESH_CONF = {"spark.rapids.tpu.shuffle.mode": "mesh"}

#: star-join aggregates, sorts, semi/anti shapes — 12 queries
MESH_QUERIES = ["q3", "q7", "q12", "q15", "q19", "q20", "q26", "q42",
                "q43", "q52", "q55", "q96"]


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    d = str(tmp_path_factory.mktemp("tpcds_mesh") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


def _canon(rows):
    from harness import canon_rows
    return canon_rows(rows)


def _eq_rows(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if math.isnan(va) and math.isnan(vb):
                    continue
                if va == vb or abs(va - vb) <= 1e-9 * max(
                        abs(va), abs(vb), 1.0):
                    continue
                return False
            elif va != vb:
                return False
    return True


@pytest.mark.parametrize("query", MESH_QUERIES)
def test_tpcds_mesh_mode(query, data_dir):
    def fn(s):
        tpcds.register(s, data_dir)
        return s.sql(tpcds.QUERIES[query]).collect()
    cpu = _canon(with_cpu_session(fn))
    tpu = _canon(with_tpu_session(fn, conf=MESH_CONF))
    assert _eq_rows(cpu, tpu), f"{query}: mesh-mode rows differ"


def test_mesh_execs_engage_somewhere(data_dir):
    """At least one of the subset's plans actually places a Mesh exec
    (the conf must not be a silent no-op)."""
    hits = []

    def probe(s):
        tpcds.register(s, data_dir)
        for q in MESH_QUERIES:
            text = s.explain(s.sql(tpcds.QUERIES[q])._plan)
            if "TpuMesh" in text:
                hits.append(q)
        return hits
    with_tpu_session(probe, conf=MESH_CONF)
    assert hits, "no query in the subset engaged a mesh exec"
