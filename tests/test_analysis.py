"""Static verification layer tests: plan invariant verifier + lint.

Verifier half: hand-built malformed plans — schema mismatch, unsupported
dtype, FINAL aggregate without an exchange, partition-count skew, missing
cancellation checkpoint — assert each pass fires, violations aggregate
(never first-failure-only), and the annotated tree renders per-node
verdicts.  Lint half: self-tests over seeded bad-code buffers and the
committed fixture files, plus the shipped-tree-is-clean assertion that
doubles as the docgen-currency gate.
"""
import importlib.util
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu.analysis import (PlanVerificationError, verify_plan,
                                       verify_or_raise)
from spark_rapids_tpu.analysis import lint as AL
from spark_rapids_tpu.analysis.plan_verify import (CKPT, DTYPE, PART,
                                                   SCHEMA)
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar.schema import Field, Schema
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.exchange import (TpuCoalescePartitions,
                                            TpuShuffleExchange)
from spark_rapids_tpu.exec.tpu_aggregate import TpuHashAggregate
from spark_rapids_tpu.exec.tpu_basic import TpuLocalScan, TpuProject
from spark_rapids_tpu.exec.tpu_join import TpuShuffledHashJoin
from spark_rapids_tpu.expr import core as ec
from spark_rapids_tpu.shuffle.partitioners import HashPartitioner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


# ---------------------------------------------------------------------------
# plan-building helpers
# ---------------------------------------------------------------------------

def _table(n=8):
    return pa.table({"a": pa.array(range(n), pa.int64()),
                     "b": pa.array([float(i) for i in range(n)],
                                   pa.float64())})


def _scan(parts=1):
    return TpuLocalScan(_table(), num_partitions=parts)


def _attr(name, dt=T.INT64):
    return ec.AttributeReference(name, dt)


class _UnsupportedBinary(T.DType):
    name = "binary"


class _BinaryLeaf(PhysicalPlan):
    """Leaf whose output schema carries a dtype no TypeSig admits."""

    @property
    def output_schema(self):
        return Schema([Field("x", _UnsupportedBinary(), True)])

    def execute(self):
        return [iter([])]


class _JoinLogical:
    """Minimal stand-in for a logical Join feeding TpuShuffledHashJoin."""

    join_type = "inner"

    def __init__(self, schema, left_keys, right_keys):
        self.schema = schema
        self.left_keys = left_keys
        self.right_keys = right_keys


def _shuffled_join(left_n, right_n):
    ls, rs = _scan(), _scan()
    lex = TpuShuffleExchange(ls, HashPartitioner([_attr("a")], left_n))
    rex = TpuShuffleExchange(rs, HashPartitioner([_attr("a")], right_n))
    schema = Schema(list(ls.output_schema) + list(rs.output_schema))
    return TpuShuffledHashJoin(
        _JoinLogical(schema, [_attr("a")], [_attr("a")]),
        lex, rex, build_right=True)


# ---------------------------------------------------------------------------
# verifier: good plans pass
# ---------------------------------------------------------------------------

class TestVerifierGoodPlans:
    def test_project_over_scan(self):
        plan = TpuProject([_attr("a"), _attr("b", T.FLOAT64)], _scan())
        assert verify_plan(plan).ok

    def test_final_agg_over_exchange(self):
        agg = TpuHashAggregate([_attr("a")], [],
                               TpuCoalescePartitions(_scan()),
                               mode="final")
        report = verify_plan(agg, passes=[PART])
        assert report.ok

    def test_shuffled_join_copartitioned(self):
        plan = _shuffled_join(4, 4)
        report = verify_plan(plan, passes=[SCHEMA, PART])
        assert report.ok, report.violations

    def test_verify_or_raise_returns_report(self):
        plan = TpuProject([_attr("a")], _scan())
        report = verify_or_raise(plan)
        assert report.ok and report.plan is plan


# ---------------------------------------------------------------------------
# verifier: each malformed-plan fixture trips its pass
# ---------------------------------------------------------------------------

class TestVerifierMalformedPlans:
    def test_schema_mismatch_unbound_attribute(self):
        # projection references a column the child never produces
        plan = TpuProject([_attr("zzz")], _scan())
        report = verify_plan(plan)
        assert not report.ok
        vs = [v for v in report.violations if v.rule == SCHEMA]
        assert vs and "zzz" in vs[0].message
        assert vs[0].node_index == 0    # anchored to the projection

    def test_schema_unresolvable_output(self):
        # untyped attribute: the projection cannot even render its own
        # output schema
        plan = TpuProject([ec.AttributeReference("zzz")], _scan())
        report = verify_plan(plan)
        assert any(v.rule == SCHEMA and "unresolvable" in v.message
                   for v in report.violations)

    def test_unsupported_dtype(self):
        report = verify_plan(TpuProject([_attr("x", _UnsupportedBinary())],
                                        _BinaryLeaf()))
        vs = [v for v in report.violations if v.rule == DTYPE]
        assert vs and any("binary" in v.message for v in vs)

    def test_final_aggregate_missing_exchange(self):
        agg = TpuHashAggregate([_attr("a")], [], _scan(), mode="final")
        report = verify_plan(agg, passes=[PART])
        assert [v.rule for v in report.violations] == [PART]
        assert "exchange" in report.violations[0].message

    def test_partial_aggregate_without_final_ancestor(self):
        agg = TpuHashAggregate([_attr("a")], [], _scan(), mode="partial")
        report = verify_plan(agg, passes=[PART])
        assert any("FINAL ancestor" in v.message
                   for v in report.violations)

    def test_partition_count_skew(self):
        plan = _shuffled_join(4, 2)
        report = verify_plan(plan, passes=[PART])
        assert len(report.violations) == 1
        v = report.violations[0]
        assert v.rule == PART and "left=4 right=2" in v.message

    def test_shuffle_arity_and_empty_keys(self):
        ex = TpuShuffleExchange(_scan(), HashPartitioner([], 0))
        report = verify_plan(ex, passes=[PART])
        msgs = "\n".join(v.message for v in report.violations)
        assert "positive int" in msgs and "no partitioning keys" in msgs

    def test_missing_cancellation_checkpoint(self):
        # locally defined materializer: its source has no timed/
        # cancel_checkpoint region and nothing below it checkpoints
        class TpuSort(PhysicalPlan):   # name places it in _MATERIALIZING
            @property
            def output_schema(self):
                return self.children[0].output_schema

            def execute(self):
                return [iter(sorted([]))]

        class _PlainLeaf(PhysicalPlan):
            @property
            def output_schema(self):
                return Schema([Field("a", T.INT64, True)])

            def execute(self):
                return [iter([])]

        report = verify_plan(TpuSort(_PlainLeaf()), passes=[CKPT])
        assert [v.rule for v in report.violations] == [CKPT]

    def test_real_sort_is_checkpoint_covered(self):
        from spark_rapids_tpu.exec.tpu_sort import TpuSort
        from spark_rapids_tpu.plan.logical import SortOrder
        plan = TpuSort([SortOrder(_attr("a"), True)], _scan())
        assert verify_plan(plan, passes=[CKPT]).ok

    def test_multi_violation_error_lists_everything(self):
        # skewed join whose projection also references a ghost column:
        # one raise carries BOTH failures plus the annotated tree
        plan = TpuProject([_attr("ghost")], _shuffled_join(4, 2))
        with pytest.raises(PlanVerificationError) as ei:
            verify_or_raise(plan)
        err = ei.value
        rules = {v.rule for v in err.violations}
        assert {SCHEMA, PART} <= rules
        text = str(err)
        assert "ghost" in text and "left=4 right=2" in text
        assert "[!!" in text and "[ok]" in text   # annotated tree


# ---------------------------------------------------------------------------
# annotated tree plumbing (satellite: tree_string annotation mode)
# ---------------------------------------------------------------------------

class TestAnnotatedTree:
    def test_default_tree_string_unchanged(self):
        plan = TpuProject([_attr("a")], _scan())
        assert plan.tree_string() == plan.tree_string(annotate=None)
        assert "[ok]" not in plan.tree_string()

    def test_annotations_append_per_node(self):
        plan = TpuProject([_attr("ghost")], _scan())
        report = verify_plan(plan)
        tree = report.annotated_tree()
        lines = tree.splitlines()
        assert len(lines) == 2
        assert "[!!" in lines[0] and "ghost" in lines[0]
        assert lines[1].rstrip().endswith("[ok]")
        # indentation (the positional join key of tools/report.py) is
        # untouched by annotations
        plain = plan.tree_string().splitlines()
        for got, want in zip(lines, plain):
            assert got.startswith(want)

    def test_report_renders_verify_column(self):
        from spark_rapids_tpu.tools.report import plan_time_shares
        plan = TpuProject([_attr("ghost")], _scan())
        rep = verify_plan(plan)
        record = {
            "physical_plan": plan.tree_string(),
            "node_metrics": {},
            "plan_verify": {
                "ok": rep.ok,
                "violations": [{"node_index": v.node_index,
                                "rule": v.rule,
                                "message": v.message}
                               for v in rep.violations]},
        }
        rows = plan_time_shares(record)
        assert rows[0]["verify"].startswith("[!!")
        assert rows[1]["verify"] == "[ok]"


# ---------------------------------------------------------------------------
# lint self-tests (seeded bad-code buffers)
# ---------------------------------------------------------------------------

class TestLint:
    def test_lock_inversion_detected(self):
        src = (
            "import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n"
            "def f():\n"
            "    with a:\n"
            "        with b:\n"
            "            pass\n"
            "def g():\n"
            "    with b:\n"
            "        with a:\n"
            "            pass\n")
        rules = {f.rule for f in AL.lint_source(src, "x.py")}
        assert AL.LOCK002 in rules

    def test_blocking_call_under_lock(self):
        src = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1)\n")
        fs = AL.lint_source(src, "x.py")
        assert any(f.rule == AL.LOCK001 and "sleep" in f.message
                   for f in fs)

    def test_condition_wait_not_flagged(self):
        src = (
            "import threading\n"
            "_cv = threading.Condition()\n"
            "def f():\n"
            "    with _cv:\n"
            "        _cv.wait()\n")
        assert AL.lint_source(src, "x.py") == []

    def test_nested_function_not_attributed_to_lock(self):
        src = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        def later():\n"
            "            time.sleep(1)\n"
            "        return later\n")
        assert AL.lint_source(src, "x.py") == []

    def test_flush_under_lock_direct(self):
        src = (
            "import threading\n"
            "from spark_rapids_tpu.columnar import pending\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        pending.flush()\n")
        fs = AL.lint_source(src, "x.py")
        assert any(f.rule == AL.LOCK003 and "flush" in f.message
                   for f in fs)

    def test_flush_under_lock_via_helper(self):
        src = (
            "import threading\n"
            "from spark_rapids_tpu.columnar import pending\n"
            "_lock = threading.Lock()\n"
            "def drain():\n"
            "    pending.flush()\n"
            "def f():\n"
            "    with _lock:\n"
            "        drain()\n")
        fs = AL.lint_source(src, "x.py")
        assert any(f.rule == AL.LOCK003 and "drain" in f.message
                   for f in fs)

    def test_file_flush_not_flagged(self):
        # file-handle / trace-buffer flushes are not device barriers
        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "def f(fh):\n"
            "    with _lock:\n"
            "        fh.flush()\n")
        assert AL.lint_source(src, "x.py") == []

    def test_flush_outside_lock_not_flagged(self):
        src = (
            "from spark_rapids_tpu.columnar import pending\n"
            "def f():\n"
            "    pending.flush()\n")
        assert AL.lint_source(src, "x.py") == []

    def test_host_sync_in_kernel_scope(self):
        src = ("import jax, numpy as np\n"
               "def k(x):\n"
               "    jax.device_get(x)\n"
               "    np.asarray(x)\n")
        fs = AL.lint_source(src, "kernels/bad.py",
                            scopes={AL.SYNC001})
        assert len(fs) == 2 and all(f.rule == AL.SYNC001 for f in fs)

    def test_sync_allowlist_exempts_asarray_only(self):
        src = ("import jax, numpy as np\n"
               "def k(x):\n"
               "    jax.device_get(x)\n"
               "    np.asarray(x)\n")
        fs = AL.lint_source(src, "exec/tpu_sort.py",
                            scopes={AL.SYNC001})
        assert [f.rule for f in fs] == [AL.SYNC001]
        assert "device_get" in fs[0].message

    def test_undocumented_conf(self):
        fs = AL.conf_doc_findings(
            {"spark.rapids.tpu.sql.enabled",
             "spark.rapids.tpu.brand.new.key"},
            set(),
            "only `spark.rapids.tpu.sql.enabled` is documented")
        assert len(fs) == 1
        assert fs[0].rule == AL.CONF001
        assert "brand.new.key" in fs[0].message

    def test_stale_documented_conf(self):
        fs = AL.conf_doc_findings(
            {"spark.rapids.tpu.sql.enabled"}, set(),
            "`spark.rapids.tpu.sql.enabled` and "
            "`spark.rapids.tpu.gone.key`")
        assert len(fs) == 1 and "gone.key" in fs[0].message

    def test_internal_confs_tolerated_in_docs(self):
        fs = AL.conf_doc_findings(
            {"spark.rapids.tpu.sql.enabled"},
            {"spark.rapids.tpu.internal.knob"},
            "`spark.rapids.tpu.sql.enabled` "
            "`spark.rapids.tpu.internal.knob`")
        assert fs == []

    def test_hygiene_rules(self):
        src = ("import time\n"
               "class BadExec(TpuExec):\n"
               "    def execute(self):\n"
               "        try:\n"
               "            return time.time()\n"
               "        except:\n"
               "            return None\n")
        rules = sorted(f.rule for f in AL.lint_source(src, "x.py"))
        assert rules == [AL.HYG001, AL.HYG002, AL.HYG003]

    def test_exec_schema_via_same_file_base(self):
        src = ("class Base(TpuExec):\n"
               "    @property\n"
               "    def output_schema(self):\n"
               "        return None\n"
               "class Child(Base):\n"
               "    def execute(self):\n"
               "        return []\n")
        assert AL.lint_source(src, "x.py", scopes={AL.HYG003}) == []

    def test_cross_file_base_stays_permissive(self):
        src = ("class Child(SomewhereElse):\n"
               "    def execute(self):\n"
               "        return []\n")
        assert AL.lint_source(src, "x.py", scopes={AL.HYG003}) == []

    def test_suppression_trailing_and_comment_only(self):
        src = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        # lint: allow(LOCK001): intentional pacing\n"
            "        time.sleep(1)\n"
            "def g():\n"
            "    try:\n"
            "        return 1\n"
            "    except:  # lint: allow(HYG001): fixture\n"
            "        return None\n")
        assert AL.lint_source(src, "x.py") == []

    def test_suppression_is_rule_specific(self):
        src = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        # lint: allow(HYG001): wrong rule id\n"
            "        time.sleep(1)\n")
        assert any(f.rule == AL.LOCK001
                   for f in AL.lint_source(src, "x.py"))

    def test_syntax_error_is_reported_not_raised(self):
        fs = AL.lint_source("def f(:\n", "x.py")
        assert len(fs) == 1 and "syntax error" in fs[0].message

    def test_flight_record_allocating_args_flagged(self):
        src = ("from spark_rapids_tpu.obs import flight as _flight\n"
               "def k(x, n):\n"
               "    _flight.record(_flight.EV_KERNEL, f'gather:{n}')\n"
               "    _flight.record('kernel', 'gather', a={'rows': n})\n"
               "    _flight.record('kernel', 'g:{}'.format(n))\n")
        fs = AL.lint_source(src, "kernels/bad.py",
                            scopes={AL.OBS002})
        assert len(fs) == 3 and all(f.rule == AL.OBS002 for f in fs)
        msgs = "\n".join(f.message for f in fs)
        assert "f-string" in msgs and "container literal" in msgs

    def test_flight_record_lazy_call_site_clean(self):
        src = ("from spark_rapids_tpu.obs import flight\n"
               "def k(x, n):\n"
               "    flight.record(flight.EV_KERNEL, 'gather', a=n, b=0)\n")
        assert AL.lint_source(src, "kernels/ok.py",
                              scopes={AL.OBS002}) == []

    def test_flight_record_rule_scoped_to_hot_path(self):
        # same allocating call is fine outside kernels/ / exec/tpu_*
        # (the service layer formats eagerly where latency is cheap)
        src = ("from spark_rapids_tpu.obs import flight as _flight\n"
               "def f(n):\n"
               "    _flight.record('state', f'shed:{n}')\n")
        scopes = AL._scopes_for("service/server.py")
        assert AL.OBS002 not in scopes
        assert AL.lint_source(src, "service/server.py",
                              scopes=scopes) == []
        assert AL.OBS002 in AL._scopes_for("exec/tpu_sort.py")
        assert AL.OBS002 in AL._scopes_for(
            "spark_rapids_tpu/kernels/gather.py")

    def test_compile_layer_in_sync_and_lock_scopes(self):
        # the superstage compiler eliminates host round trips; its own
        # files must not reintroduce them (SYNC001) nor block under the
        # stage locks of the drains it runs inside (LOCK001/LOCK002)
        for rel in ("spark_rapids_tpu/compile/carve.py",
                    "spark_rapids_tpu/compile/lower.py",
                    "spark_rapids_tpu/exec/superstage.py"):
            scopes = AL._scopes_for(rel)
            assert AL.SYNC001 in scopes, rel
            assert AL.LOCK001 in scopes and AL.LOCK002 in scopes, rel
        src = ("import jax\n"
               "def carve(dev):\n"
               "    return jax.device_get(dev)\n")
        fs = AL.lint_source(
            src, "spark_rapids_tpu/compile/carve.py",
            scopes=AL._scopes_for("spark_rapids_tpu/compile/carve.py"))
        assert any(f.rule == AL.SYNC001 for f in fs)

    def test_memplane_in_sync_and_obs_scopes(self):
        # the memory plane prices spills from catalog transitions the
        # memory layer already makes; its own file must not pull device
        # buffers (SYNC001) nor allocate per flight event (OBS002)
        scopes = AL._scopes_for("spark_rapids_tpu/obs/memplane.py")
        assert AL.SYNC001 in scopes
        assert AL.OBS002 in scopes
        src = ("import jax\n"
               "def note_spill(dev):\n"
               "    return jax.device_get(dev)\n")
        fs = AL.lint_source(src, "spark_rapids_tpu/obs/memplane.py",
                            scopes=scopes)
        assert any(f.rule == AL.SYNC001 for f in fs)


# ---------------------------------------------------------------------------
# CLI + project surface
# ---------------------------------------------------------------------------

def _cli():
    spec = importlib.util.spec_from_file_location(
        "ci_lint", os.path.join(REPO_ROOT, "ci", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCliAndProject:
    @pytest.mark.parametrize("fixture", [
        "lock_inversion.py", "host_sync_kernel.py", "bad_hygiene.py",
        "flight_alloc.py", "superstage_sync.py", "flush_under_lock.py",
        "memplane_sync.py", "obs_overhead.py"])
    def test_cli_nonzero_on_each_seeded_fixture(self, fixture, capsys):
        assert _cli().main([os.path.join(FIXTURES, fixture)]) == 1
        out = capsys.readouterr().out
        assert "finding(s)" in out

    def test_cli_zero_on_suppressed_fixture(self, capsys):
        path = os.path.join(FIXTURES, "suppressed_clean.py")
        assert _cli().main([path]) == 0

    def test_shipped_tree_lints_clean(self):
        # the full CI gate: scoped AST rules + conf/doc drift + docgen
        # currency.  A failure here means a true finding shipped or
        # docs/*.md were not regenerated after a registry change.
        findings = AL.lint_project(REPO_ROOT)
        assert findings == [], AL.format_findings(findings)

    def test_planner_hook_invokes_verifier(self, monkeypatch):
        import spark_rapids_tpu.analysis.plan_verify as pv
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        calls = []
        real = pv.verify_or_raise
        monkeypatch.setattr(
            pv, "verify_or_raise",
            lambda plan, passes=None: calls.append(plan) or
            real(plan, passes))
        s = TpuSession(TpuConf({"spark.rapids.tpu.sql.planVerify": True}))
        df = s.create_dataframe(_table())
        df.collect()
        assert calls, "Planner.plan never reached the verifier hook"
