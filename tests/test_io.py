"""IO layer tests: parquet/csv/orc scans (all reader strategies) + writers.

Reference pattern: parquet_test.py / orc_test.py / csv_test.py.
"""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from spark_rapids_tpu.api import functions as F

from harness import (assert_tpu_and_cpu_are_equal_collect, with_tpu_session,
                     with_cpu_session)
from data_gen import IntGen, FloatGen, StringGen, KeyGen, gen_table

N = 250


@pytest.fixture
def pq_dir(tmp_path, rng):
    """A directory of several small parquet files."""
    data = gen_table({"k": KeyGen(cardinality=9), "i": IntGen(),
                      "f": FloatGen(), "s": StringGen()}, N)
    t = pa.table(data)
    d = tmp_path / "pq"
    d.mkdir()
    per = N // 3
    for i in range(3):
        papq.write_table(t.slice(i * per, per if i < 2 else N - 2 * per),
                         d / f"f{i}.parquet")
    return str(d)


class TestParquetScan:
    def test_read_matches_cpu(self, pq_dir):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(pq_dir))

    @pytest.mark.parametrize("strategy",
                             ["PERFILE", "MULTITHREADED", "COALESCING"])
    def test_reader_strategies(self, pq_dir, strategy):
        conf = {"spark.rapids.tpu.sql.format.parquet.reader.type": strategy}
        rows = with_tpu_session(
            lambda s: s.read.parquet(pq_dir).collect(), conf)
        assert len(rows) == N

    def test_scan_filter_agg(self, pq_dir):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(pq_dir)
            .filter(F.col("i") > 0)
            .group_by("k").agg(F.sum("f").alias("sf"),
                               F.count().alias("c")))

    def test_write_roundtrip(self, pq_dir, tmp_path):
        out = str(tmp_path / "out_pq")

        def write_and_read(s):
            s.read.parquet(pq_dir).filter(F.col("i") > 0) \
                .write.parquet(out)
            return s.read.parquet(out)
        rows1 = with_tpu_session(lambda s: write_and_read(s).collect())
        rows2 = with_cpu_session(lambda s: write_and_read(s).collect())
        assert sorted(map(str, rows1)) == sorted(map(str, rows2))
        assert any(f.startswith("part-") for f in os.listdir(out))


class TestCsv:
    def test_csv_roundtrip(self, tmp_path):
        import pyarrow.csv as pacsv
        data = gen_table({"a": IntGen(null_ratio=0),
                          "s": StringGen(null_ratio=0, charset="abcXYZ")},
                         80)
        t = pa.table(data)
        path = tmp_path / "x.csv"
        pacsv.write_csv(t, path)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.csv(str(path)))


class TestOrc:
    def test_orc_roundtrip(self, tmp_path):
        from pyarrow import orc as paorc
        data = gen_table({"a": IntGen(), "f": FloatGen(),
                          "s": StringGen()}, 90)
        t = pa.table(data)
        path = tmp_path / "x.orc"
        paorc.write_table(t, path)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.orc(str(path)))


class TestPushdown:
    def test_filter_pushdown_into_scan(self, pq_dir):
        from harness import with_tpu_session
        from spark_rapids_tpu.io.planner import TpuFileScan

        def fn(s):
            df = s.read.parquet(pq_dir).filter(
                (F.col("i") > 0) & (F.col("k") < 5))
            phys = s._plan(df._plan)
            scans = [n for n in phys.collect_nodes()
                     if isinstance(n, TpuFileScan)]
            assert scans and scans[0].pushed_filters, \
                "filters not pushed into scan"
            return df
        rows = with_tpu_session(lambda s: fn(s).collect())
        # equality with CPU engine (no pushdown there -> same answer)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(pq_dir).filter(
                (F.col("i") > 0) & (F.col("k") < 5)))


class TestPartitionedIO:
    """Hive-style partitioned writes + partition-column discovery reads.

    Reference: GpuFileFormatWriter dynamic partitioning +
    ColumnarPartitionReaderWithPartitionValues (SURVEY.md §2.6)."""

    def _df(self, s):
        import numpy as np
        rng = np.random.default_rng(9)
        return s.create_dataframe({
            "year": rng.choice([2020, 2021], 40).astype("int64"),
            "cat": rng.choice(["a", "b"], 40),
            "v": rng.integers(0, 100, 40).astype("int64"),
        })

    def test_partitioned_write_layout(self, tmp_path):
        from harness import with_tpu_session
        out = str(tmp_path / "p")

        def run(s):
            self._df(s).write.partition_by("year", "cat").parquet(out)
            return []
        with_tpu_session(run)
        import os
        years = sorted(d for d in os.listdir(out) if d.startswith("year="))
        assert years == ["year=2020", "year=2021"]
        assert any(d.startswith("cat=") for d in
                   os.listdir(os.path.join(out, years[0])))

    def test_partitioned_roundtrip_both_engines(self, tmp_path):
        from harness import (assert_tpu_and_cpu_are_equal_collect,
                             with_cpu_session)
        out = str(tmp_path / "rt")

        def write(s):
            self._df(s).write.partition_by("year").parquet(out)
            return []
        with_cpu_session(write)

        def read(s):
            df = s.read.parquet(out)
            # partition col is discovered and appended, typed int64
            assert df.schema["year"].dtype.name == "bigint" or \
                df.schema["year"].dtype.name == "long", df.schema
            return df.group_by("year").count()
        assert_tpu_and_cpu_are_equal_collect(read)

    def test_partition_pruning_filter(self, tmp_path):
        from harness import assert_tpu_and_cpu_are_equal_collect, \
            with_cpu_session
        out = str(tmp_path / "pr")

        def write(s):
            self._df(s).write.partition_by("cat").parquet(out)
            return []
        with_cpu_session(write)
        from spark_rapids_tpu.api import functions as F
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(out).filter(F.col("cat") == "a")
            .group_by("cat").count())

    def test_unpartitioned_overwrite_clears_partition_dirs(self, tmp_path):
        from harness import with_cpu_session
        out = str(tmp_path / "ow")

        def run(s):
            self._df(s).write.partition_by("cat").parquet(out)
            small = s.create_dataframe({"cat": ["c"], "v": [9]})
            small.write.parquet(out)
            return s.read.parquet(out).collect()
        rows = with_cpu_session(run)
        assert rows == [("c", 9)], rows

    def test_null_and_special_partition_values(self, tmp_path):
        from harness import with_cpu_session
        out = str(tmp_path / "np")

        def run(s):
            df = s.create_dataframe({"year": [2020, 2021, None],
                                     "v": [1, 2, 3]})
            df.write.partition_by("year").parquet(out)
            got = sorted(s.read.parquet(out).select("v", "year").collect())
            assert got == [(1, 2020), (2, 2021), (3, None)], got
            df2 = s.create_dataframe({"cat": ["a/b", "c"], "v": [1, 2]})
            df2.write.partition_by("cat").parquet(str(tmp_path / "sp"))
            got2 = sorted(s.read.parquet(str(tmp_path / "sp"))
                          .select("v", "cat").collect())
            assert got2 == [(1, "a/b"), (2, "c")], got2
            return []
        with_cpu_session(run)

    def test_mixed_partition_value_types_infer_string(self, tmp_path):
        from harness import with_cpu_session
        out = str(tmp_path / "mx")

        def run(s):
            df = s.create_dataframe({"k": ["0", "abc"], "v": [1, 2]})
            df.write.partition_by("k").parquet(out)
            got = sorted(s.read.parquet(out).select("v", "k").collect())
            assert got == [(1, "0"), (2, "abc")], got
            return []
        with_cpu_session(run)


def test_alluxio_style_path_rewrite(tmp_path):
    """spark.rapids.tpu.alluxio.pathsToReplace rewrites scan path
    prefixes before reading (RapidsConf.scala:1072 role)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as papq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.config import TpuConf
    real = tmp_path / "mirror"
    real.mkdir()
    papq.write_table(pa.table({"x": np.arange(10, dtype=np.int64)}),
                     str(real / "t.parquet"))
    s = TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": True,
        "spark.rapids.tpu.alluxio.pathsToReplace":
            f"/nonexistent/cold->{tmp_path}/mirror",
    }))
    df = s.read.parquet("/nonexistent/cold/t.parquet")
    assert sorted(r[0] for r in df.collect()) == list(range(10))


class TestDeviceScanCache:
    """Device-resident scan cache (io/scan_cache.py): repeat scans of
    unchanged files replay uploaded batches instead of re-reading."""

    def _session(self, **extra):
        from spark_rapids_tpu.api import TpuSession
        from spark_rapids_tpu.config import TpuConf
        conf = {"spark.rapids.tpu.sql.enabled": True}
        conf.update(extra)
        return TpuSession(TpuConf(conf))

    @pytest.fixture(autouse=True)
    def fresh_cache(self):
        from spark_rapids_tpu.io.scan_cache import DeviceScanCache
        DeviceScanCache.get().clear()
        yield
        DeviceScanCache.get().clear()

    def test_repeat_scan_hits_cache(self, pq_dir, monkeypatch):
        from spark_rapids_tpu.io import planner as iop
        from spark_rapids_tpu.io.scan_cache import DeviceScanCache
        reads = {"n": 0}
        orig = iop.FilePartitionReader._read

        def counting(self, pair):
            reads["n"] += 1
            return orig(self, pair)
        monkeypatch.setattr(iop.FilePartitionReader, "_read", counting)
        s = self._session()
        df = s.read.parquet(pq_dir)
        from harness import canon_rows as canon
        a = canon(df.collect())
        first_reads = reads["n"]
        assert first_reads > 0
        b = canon(s.read.parquet(pq_dir).collect())
        assert reads["n"] == first_reads, "second scan must not re-read"
        assert DeviceScanCache.get().hits >= 1
        assert a == b

    def test_modified_file_invalidates(self, pq_dir, monkeypatch):
        import time
        s = self._session()
        before = s.read.parquet(pq_dir).collect()
        f = os.path.join(pq_dir, "f0.parquet")
        t = papq.read_table(f)
        time.sleep(0.01)
        papq.write_table(t.slice(0, 10), f)  # rewrite -> new mtime/size
        after = s.read.parquet(pq_dir).collect()
        assert len(after) < len(before)

    def test_limit_prefix_not_cached(self, pq_dir):
        from spark_rapids_tpu.io.scan_cache import DeviceScanCache
        s = self._session()
        few = s.read.parquet(pq_dir).limit(3).collect()
        assert len(few) == 3
        # a short-circuited scan must not poison the cache
        assert DeviceScanCache.get().nbytes == 0 or \
            len(s.read.parquet(pq_dir).collect()) == N
        assert len(s.read.parquet(pq_dir).collect()) == N

    def test_byte_budget_evicts(self, pq_dir):
        from spark_rapids_tpu.io.scan_cache import DeviceScanCache
        s = self._session(**{
            "spark.rapids.tpu.io.deviceScanCache.bytes": 1})
        s.read.parquet(pq_dir).collect()
        assert DeviceScanCache.get().nbytes == 0

    def test_disabled_by_conf(self, pq_dir, monkeypatch):
        from spark_rapids_tpu.io import planner as iop
        reads = {"n": 0}
        orig = iop.FilePartitionReader._read

        def counting(self, pair):
            reads["n"] += 1
            return orig(self, pair)
        monkeypatch.setattr(iop.FilePartitionReader, "_read", counting)
        s = self._session(**{
            "spark.rapids.tpu.io.deviceScanCache.enabled": False})
        s.read.parquet(pq_dir).collect()
        n1 = reads["n"]
        s.read.parquet(pq_dir).collect()
        assert reads["n"] == 2 * n1

    def test_options_and_dtypes_key_the_cache(self, tmp_path):
        """Same file read with different parse options or column dtypes
        must NOT collide in the device cache (silent wrong results)."""
        from spark_rapids_tpu.columnar.schema import Schema
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.io.planner import TpuFileScan
        from spark_rapids_tpu.plan import logical as L
        f = tmp_path / "t.csv"
        f.write_text("a,b\n1,2\n")
        conf = TpuConf({"spark.rapids.tpu.sql.enabled": True})

        def key(options, ddl):
            scan = TpuFileScan(
                L.Scan("csv", [str(f)], Schema.from_ddl(ddl), options),
                conf)
            return scan._cache_key(1 << 20)
        base = key({"sep": ","}, "a string, b string")
        assert base is not None
        assert key({"sep": "|"}, "a string, b string") != base
        assert key({"sep": ","}, "a long, b string") != base
        assert key({"sep": ","}, "a string, b string") == base

    def test_pressure_clears_cache(self, pq_dir):
        from spark_rapids_tpu.io.scan_cache import (DeviceScanCache,
                                                    clear_on_pressure)
        s = self._session()
        s.read.parquet(pq_dir).collect()
        assert DeviceScanCache.get().nbytes > 0
        clear_on_pressure()
        assert DeviceScanCache.get().nbytes == 0


class TestWriteCommitProtocol:
    """Atomic task-commit writes (GpuFileFormatWriter +
    BasicColumnarWriteStatsTracker roles): temp-dir attempts, atomic
    rename on commit, clean abort on failure, _SUCCESS marker, and
    rows/bytes/files stats."""

    def _write(self, s, out, n=200, partition_by=None):
        import numpy as np
        df = s.create_dataframe(
            {"k": np.arange(n, dtype=np.int64) % 4,
             "v": np.arange(n, dtype=np.int64)}, num_partitions=2)
        w = df.write
        if partition_by:
            w = w.partition_by(*partition_by)
        w.parquet(out)
        return df

    def test_success_marker_and_no_temp_dirs(self, tmp_path):
        from tests.harness import with_tpu_session
        out = str(tmp_path / "t1")
        with_tpu_session(lambda s: self._write(s, out))
        names = sorted(os.listdir(out))
        assert "_SUCCESS" in names
        assert not [n for n in names if n.startswith("_temporary")]
        assert [n for n in names if n.startswith("part-")]

    def test_write_stats_tracking(self, tmp_path):
        """WriteCommitProtocol stats (BasicColumnarWriteStatsTracker
        role): numFiles/numOutputBytes/numOutputRows and DISTINCT
        numParts across tasks."""
        from spark_rapids_tpu.io.planner import WriteCommitProtocol
        out = str(tmp_path / "t2")
        os.makedirs(out)
        proto = WriteCommitProtocol(out)
        proto.setup_job()
        for task, rows in ((0, 10), (1, 7)):
            d = proto.task_dir(task)
            for part in ("k=0", "k=1"):
                os.makedirs(os.path.join(d, part), exist_ok=True)
                with open(os.path.join(d, part,
                                       f"part-{task:05d}.parquet"),
                          "wb") as f:
                    f.write(b"x" * 100)
            proto.commit_task(task, rows)
        proto.commit_job()
        assert proto.stats["numFiles"] == 4
        assert proto.stats["numOutputBytes"] == 400
        assert proto.stats["numOutputRows"] == 17
        # k=0 and k=1 are DISTINCT partitions regardless of task count
        assert proto.stats["numParts"] == 2
        assert os.path.exists(os.path.join(out, "_SUCCESS"))
        assert os.path.exists(os.path.join(out, "k=0",
                                           "part-00000.parquet"))

    def test_overwrite_failure_keeps_old_data(self, tmp_path,
                                              monkeypatch):
        """mode=overwrite deletes the previous dataset at JOB COMMIT:
        a failed overwrite leaves the old dataset intact."""
        from tests.harness import with_tpu_session
        from spark_rapids_tpu.io import planner as P
        out = str(tmp_path / "t2b")
        with_tpu_session(lambda s: self._write(s, out, n=30))
        old = sorted(n for n in os.listdir(out)
                     if n.startswith("part-"))
        assert old

        def boom(fmt, table, base):
            raise RuntimeError("disk exploded")
        monkeypatch.setattr(P, "_write_table", boom)
        import pytest as _pytest

        def overwrite(s):
            import numpy as np
            df = s.create_dataframe(
                {"k": np.zeros(5, np.int64), "v": np.zeros(5, np.int64)})
            df.write.mode("overwrite").parquet(out)
        with _pytest.raises(Exception, match="disk exploded"):
            with_tpu_session(overwrite)
        now = sorted(n for n in os.listdir(out)
                     if n.startswith("part-"))
        assert now == old            # old dataset untouched
        assert "_SUCCESS" in os.listdir(out)

    def test_hidden_partition_column_rejected(self, tmp_path):
        from tests.harness import with_tpu_session
        import pytest as _pytest
        out = str(tmp_path / "t2c")
        with _pytest.raises(Exception, match="partition column"):
            with_tpu_session(
                lambda s: self._write(s, out, partition_by=["_k"]))

    def test_abort_leaves_target_clean(self, tmp_path, monkeypatch):
        from tests.harness import with_tpu_session
        from spark_rapids_tpu.io import planner as P
        out = str(tmp_path / "t3")
        calls = {"n": 0}
        orig = P._write_table

        def boom(fmt, table, base):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise RuntimeError("disk exploded")
            return orig(fmt, table, base)
        monkeypatch.setattr(P, "_write_table", boom)
        import pytest as _pytest
        with _pytest.raises(Exception, match="disk exploded"):
            with_tpu_session(lambda s: self._write(s, out))
        # the failed job must leave no partial part files, no marker,
        # and no temp dirs in the target
        leftover = [n for n in os.listdir(out)] if os.path.isdir(out) \
            else []
        assert not [n for n in leftover if n.startswith("part-")]
        assert "_SUCCESS" not in leftover
        assert not [n for n in leftover if n.startswith("_temporary")]

    def test_partitioned_commit_promotes_subdirs(self, tmp_path):
        from tests.harness import with_tpu_session
        out = str(tmp_path / "t4")
        with_tpu_session(
            lambda s: self._write(s, out, partition_by=["k"]))
        names = sorted(os.listdir(out))
        assert "_SUCCESS" in names
        subs = [n for n in names if n.startswith("k=")]
        assert len(subs) == 4
        for sub in subs:
            assert [f for f in os.listdir(os.path.join(out, sub))
                    if f.endswith(".parquet")]

    def test_scan_ignores_inflight_temp_dirs(self, tmp_path):
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as papq
        from tests.harness import with_tpu_session
        out = str(tmp_path / "t5")
        with_tpu_session(lambda s: self._write(s, out, n=50))
        # simulate a concurrent in-flight writer's attempt dir
        tdir = os.path.join(out, "_temporary-deadbeef", "task-00000")
        os.makedirs(tdir)
        papq.write_table(
            pa.table({"k": np.zeros(99, np.int64),
                      "v": np.zeros(99, np.int64)}),
            os.path.join(tdir, "part-00000.parquet"))

        def read(s):
            return s.read.parquet(out).collect()
        rows = with_tpu_session(read)
        assert len(rows) == 50          # the 99 in-flight rows invisible
