"""IO layer tests: parquet/csv/orc scans (all reader strategies) + writers.

Reference pattern: parquet_test.py / orc_test.py / csv_test.py.
"""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from spark_rapids_tpu.api import functions as F

from harness import (assert_tpu_and_cpu_are_equal_collect, with_tpu_session,
                     with_cpu_session)
from data_gen import IntGen, FloatGen, StringGen, KeyGen, gen_table

N = 250


@pytest.fixture
def pq_dir(tmp_path, rng):
    """A directory of several small parquet files."""
    data = gen_table({"k": KeyGen(cardinality=9), "i": IntGen(),
                      "f": FloatGen(), "s": StringGen()}, N)
    t = pa.table(data)
    d = tmp_path / "pq"
    d.mkdir()
    per = N // 3
    for i in range(3):
        papq.write_table(t.slice(i * per, per if i < 2 else N - 2 * per),
                         d / f"f{i}.parquet")
    return str(d)


class TestParquetScan:
    def test_read_matches_cpu(self, pq_dir):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(pq_dir))

    @pytest.mark.parametrize("strategy",
                             ["PERFILE", "MULTITHREADED", "COALESCING"])
    def test_reader_strategies(self, pq_dir, strategy):
        conf = {"spark.rapids.tpu.sql.format.parquet.reader.type": strategy}
        rows = with_tpu_session(
            lambda s: s.read.parquet(pq_dir).collect(), conf)
        assert len(rows) == N

    def test_scan_filter_agg(self, pq_dir):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(pq_dir)
            .filter(F.col("i") > 0)
            .group_by("k").agg(F.sum("f").alias("sf"),
                               F.count().alias("c")))

    def test_write_roundtrip(self, pq_dir, tmp_path):
        out = str(tmp_path / "out_pq")

        def write_and_read(s):
            s.read.parquet(pq_dir).filter(F.col("i") > 0) \
                .write.parquet(out)
            return s.read.parquet(out)
        rows1 = with_tpu_session(lambda s: write_and_read(s).collect())
        rows2 = with_cpu_session(lambda s: write_and_read(s).collect())
        assert sorted(map(str, rows1)) == sorted(map(str, rows2))
        assert any(f.startswith("part-") for f in os.listdir(out))


class TestCsv:
    def test_csv_roundtrip(self, tmp_path):
        import pyarrow.csv as pacsv
        data = gen_table({"a": IntGen(null_ratio=0),
                          "s": StringGen(null_ratio=0, charset="abcXYZ")},
                         80)
        t = pa.table(data)
        path = tmp_path / "x.csv"
        pacsv.write_csv(t, path)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.csv(str(path)))


class TestOrc:
    def test_orc_roundtrip(self, tmp_path):
        from pyarrow import orc as paorc
        data = gen_table({"a": IntGen(), "f": FloatGen(),
                          "s": StringGen()}, 90)
        t = pa.table(data)
        path = tmp_path / "x.orc"
        paorc.write_table(t, path)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.orc(str(path)))


class TestPushdown:
    def test_filter_pushdown_into_scan(self, pq_dir):
        from harness import with_tpu_session
        from spark_rapids_tpu.io.planner import TpuFileScan

        def fn(s):
            df = s.read.parquet(pq_dir).filter(
                (F.col("i") > 0) & (F.col("k") < 5))
            phys = s._plan(df._plan)
            scans = [n for n in phys.collect_nodes()
                     if isinstance(n, TpuFileScan)]
            assert scans and scans[0].pushed_filters, \
                "filters not pushed into scan"
            return df
        rows = with_tpu_session(lambda s: fn(s).collect())
        # equality with CPU engine (no pushdown there -> same answer)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read.parquet(pq_dir).filter(
                (F.col("i") > 0) & (F.col("k") < 5)))
