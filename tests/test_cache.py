"""Columnar cache (ParquetCachedBatchSerializer role) tests.

Pattern parity: reference cache_test.py (integration_tests) — cached
dataframes return identical results and serve repeat actions from the
cache.
"""
import pyarrow as pa

from spark_rapids_tpu.api import functions as F
from harness import assert_tpu_and_cpu_are_equal_collect, with_tpu_session


def _df(s):
    return s.range(0, 100, num_partitions=3).select(
        F.col("id"), (F.col("id") % 7).alias("k"),
        (F.col("id") * 1.5).alias("f"))


class TestCache:
    def test_cache_parity(self):
        def fn(s):
            df = _df(s).cache()
            df.collect()          # fill
            return df.filter(F.col("k") == 3)
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_cache_fills_and_hits(self):
        def fn(s):
            df = _df(s).cache()
            first = df.collect()
            storage = df._plan.storage
            assert storage.ready
            assert storage.nbytes() > 0
            second = df.collect()
            assert sorted(first) == sorted(second)
            return storage
        storage = with_tpu_session(fn)
        # 3 input partitions -> 3 cached blob lists
        assert len(storage.partitions()) == 3

    def test_unpersist_invalidates(self):
        def fn(s):
            df = _df(s).cache()
            df.collect()
            storage = df._plan.storage
            assert storage.ready
            df.unpersist()
            assert not storage.ready
            return df.collect()
        rows = with_tpu_session(fn)
        assert len(rows) == 100

    def test_partial_consumption_does_not_poison(self):
        def fn(s):
            df = _df(s).cache()
            # limit consumes only part of the stream: no cache fill
            few = df.limit(5).collect()
            assert len(few) == 5
            storage = df._plan.storage
            # a later full action must still be complete
            assert len(df.collect()) == 100
            return True
        assert with_tpu_session(fn)

    def test_cached_strings_and_arrays(self):
        def fn(s):
            t = pa.table({
                "s": ["aa", None, "b"],
                "l": [[1, 2], None, [3]],
            })
            df = s.create_dataframe(t).cache()
            df.collect()
            return df.select(F.size("l").alias("n"), "s")
        assert_tpu_and_cpu_are_equal_collect(fn)

    def test_cache_downstream_ops(self):
        def fn(s):
            df = _df(s).cache()
            df.collect()
            return df.group_by("k").agg(F.sum("id").alias("sv")) \
                .order_by("k")
        assert_tpu_and_cpu_are_equal_collect(fn, ignore_order=False)
