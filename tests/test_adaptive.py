"""Adaptive query execution tests.

Pattern parity: reference AdaptiveQueryExecSuite (tests/.../
AdaptiveQueryExecSuite.scala) — runtime partition coalescing, shuffled
join -> broadcast conversion, skew-join splitting, all validated against
the CPU oracle.
"""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exec.adaptive import (coalesce_partition_ids,
                                            skew_split_sizes)
from harness import assert_tpu_and_cpu_are_equal_collect, with_tpu_session


class TestPartitionPlanning:
    def test_coalesce_groups_adjacent_small(self):
        stats = [(10, 1), (10, 1), (10, 1), (100, 9), (10, 1)]
        groups = coalesce_partition_ids(stats, target_bytes=35)
        assert groups == [[0, 1, 2], [3], [4]]
        assert [pid for g in groups for pid in g] == list(range(5))

    def test_coalesce_single_when_everything_small(self):
        groups = coalesce_partition_ids([(1, 1)] * 8, target_bytes=1000)
        assert groups == [list(range(8))]

    def test_coalesce_respects_order(self):
        groups = coalesce_partition_ids([(50, 1), (60, 1), (1, 1)],
                                        target_bytes=64)
        assert groups == [[0], [1, 2]]

    def test_skew_detection(self):
        stats = [(100, 1)] * 7 + [(10_000_000_000, 1)]
        flags = skew_split_sizes(stats, factor=5.0, min_bytes=1 << 20)
        assert flags == [False] * 7 + [True]

    def test_skew_needs_min_bytes(self):
        stats = [(10, 1)] * 7 + [(1000, 1)]
        flags = skew_split_sizes(stats, factor=5.0, min_bytes=1 << 20)
        assert not any(flags)


def _tables(s, n_left=200, n_right=20):
    # repartition hides the static row estimate, forcing the runtime
    # (adaptive) join strategy decision
    left = s.range(0, n_left, num_partitions=2).select(
        (F.col("id") % 7).alias("k"), F.col("id").alias("v")) \
        .repartition(3)
    right = s.range(0, n_right, num_partitions=2).select(
        (F.col("id") % 7).alias("k2"), (F.col("id") * 10).alias("w")) \
        .repartition(3)
    return left, right


def _find(plan, cls):
    """Depth-first search for the first exec node of the given class."""
    if isinstance(plan, cls):
        return plan
    for c in plan.children:
        got = _find(c, cls)
        if got:
            return got
    return None


AQE_ON = {"spark.rapids.tpu.sql.adaptive.enabled": "true"}
AQE_OFF = {"spark.rapids.tpu.sql.adaptive.enabled": "false"}


class TestAdaptiveJoin:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                     "semi", "anti"])
    def test_join_parity_aqe_on(self, how):
        def fn(s):
            left, right = _tables(s)
            if how in ("semi", "anti"):
                return left.join(right, left["k"] == right["k2"], how)
            return left.join(right, left["k"] == right["k2"], how) \
                .select("k", "v", "w")
        assert_tpu_and_cpu_are_equal_collect(fn, conf=AQE_ON)

    def test_small_build_converts_to_broadcast(self):
        def fn(s):
            left, right = _tables(s, n_left=500, n_right=5)
            df = left.join(right, left["k"] == right["k2"], "inner")
            rows = df.collect()
            # find the adaptive join node and check its runtime strategy
            plan = df._last_physical_plan
            return rows, plan
        rows, plan = with_tpu_session(fn, conf=AQE_ON)
        from spark_rapids_tpu.exec.adaptive import TpuAdaptiveShuffledJoin
        node = _find(plan, TpuAdaptiveShuffledJoin)
        assert node is not None
        assert node.strategy == "broadcast"
        # ids 0..499 joined on id%7 against keys 0..4
        expected = sum(1 for i in range(500) if i % 7 <= 4)
        assert len(rows) == expected

    def test_large_build_stays_shuffled(self):
        conf = dict(AQE_ON)
        conf["spark.rapids.tpu.sql.adaptive.autoBroadcastJoinBytes"] = "64"

        def fn(s):
            left, right = _tables(s, n_left=100, n_right=100)
            df = left.join(right, left["k"] == right["k2"], "inner")
            df.collect()
            return df._last_physical_plan
        plan = with_tpu_session(fn, conf=conf)
        from spark_rapids_tpu.exec.adaptive import TpuAdaptiveShuffledJoin
        node = _find(plan, TpuAdaptiveShuffledJoin)
        assert node is not None
        assert node.strategy == "shuffled"

    def test_skewed_join_parity(self):
        """90% of probe rows share one key: the skew path must still
        produce oracle-identical results."""
        conf = dict(AQE_ON)
        conf["spark.rapids.tpu.sql.adaptive.skewedPartitionThresholdBytes"] \
            = "1"
        conf["spark.rapids.tpu.sql.adaptive.skewedPartitionFactor"] = "1.5"
        conf["spark.rapids.tpu.sql.adaptive.autoBroadcastJoinBytes"] = "1"
        conf["spark.rapids.tpu.sql.batchSizeRows"] = "64"

        def fn(s):
            left = s.range(0, 1000, num_partitions=2).select(
                F.when(F.col("id") % 10 == 0, F.col("id") % 5)
                .otherwise(F.lit(99)).alias("k"),
                F.col("id").alias("v"))
            right = s.range(0, 200).select(
                (F.col("id") % 100).alias("k2"),
                (F.col("id") * 3).alias("w"))
            return left.join(right, left["k"] == right["k2"], "inner") \
                .select("k", "v", "w")
        assert_tpu_and_cpu_are_equal_collect(fn, conf=conf)


class TestAdaptiveAggregate:
    def test_agg_parity_with_coalesced_read(self):
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.range(0, 500, num_partitions=4).select(
                (F.col("id") % 13).alias("g"), F.col("id").alias("v"))
            .group_by("g").agg(F.sum("v").alias("sv"),
                               F.count("*").alias("n")),
            conf=AQE_ON)

    def test_aqe_read_coalesces_small_partitions(self):
        def fn(s):
            df = s.range(0, 100, num_partitions=4).select(
                (F.col("id") % 5).alias("g"), F.col("id").alias("v")) \
                .group_by("g").agg(F.sum("v").alias("sv"))
            rows = df.collect()
            return rows, df._last_physical_plan
        rows, plan = with_tpu_session(fn, conf=AQE_ON)
        from spark_rapids_tpu.exec.adaptive import TpuAQEShuffleRead
        node = _find(plan, TpuAQEShuffleRead)
        assert node is not None
        # tiny data: everything coalesces into one read group
        assert len(node._groups) == 1
        assert len(rows) == 5
