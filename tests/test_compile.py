"""Superstage compiler tests (compile/, exec/superstage.py, PV-STAGE).

Four surfaces:

1. Carve/lower unit contract on synthetic plans — dispatch-strategy
   classification, region wrapping, min-ops threshold, unfusable-node
   ejection, resolve-at-edge for non-resolving consumers, and the
   PV-STAGE verifier pass (clean carves pass; hand-built violations of
   each carving contract are caught).
2. Engine determinism — the SAME query with superstage carving on vs
   off must produce BIT-IDENTICAL output (carving changes dispatch,
   never results): the bench-shape query hashed over its arrow IPC
   stream across the pipeline parallelism matrix, plus TPC-DS
   q3/q42/q52/q96 row-list equality.
3. The flush budget — a warm carved star-join collapses to ~one fused
   device round trip (the per-query ``flushes`` field the session now
   logs), strictly fewer than the uncarved run.
4. Fallbacks — duplicate-key builds fail the speculative join's fit
   flag and redo exactly; a failing region setup disarms and retries
   eagerly; a cancelled query unwinds from inside a superstage drain.
"""
import hashlib
import os
import sys

import numpy as np
import pyarrow as pa
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import tpcds  # noqa: E402

from harness import with_tpu_session  # noqa: E402

from spark_rapids_tpu import compile as C
from spark_rapids_tpu.analysis.plan_verify import STAGE, verify_plan
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.columnar import pending
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.exchange import TpuBroadcastExchange
from spark_rapids_tpu.exec.superstage import TpuSuperstage
from spark_rapids_tpu.exec.tpu_basic import (TpuFilter, TpuLocalLimit,
                                             TpuLocalScan, TpuProject)
from spark_rapids_tpu.expr import core as ec
from spark_rapids_tpu.expr.predicates import GreaterThan
from spark_rapids_tpu.service.cancellation import (CancelToken,
                                                   query_context)
from spark_rapids_tpu.service.errors import QueryCancelledError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _table(n=64):
    return pa.table({"a": pa.array(range(n), pa.int64()),
                     "b": pa.array([float(i) for i in range(n)],
                                   pa.float64())})


def _attr(name, dt=T.INT64):
    return ec.AttributeReference(name, dt)


def _chain(n_ops=2, parts=1):
    """Project(...Project(Filter(scan))) with ``n_ops`` member nodes."""
    node = TpuLocalScan(_table(), num_partitions=parts)
    node = TpuFilter(GreaterThan(_attr("a"), ec.Literal(3)), node)
    for _ in range(n_ops - 1):
        node = TpuProject([_attr("a"), _attr("b", T.FLOAT64)], node)
    return node


class _OpaqueExec(PhysicalPlan):
    """Unknown passthrough operator: classify() must treat it as a
    BOUNDARY, and carve must eject it from any region."""

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return self.children[0].num_partitions_hint()

    def execute(self):
        return self.children[0].execute()


# ---------------------------------------------------------------------------
# lowering classification
# ---------------------------------------------------------------------------

class TestLower:
    def test_strategies(self):
        scan = TpuLocalScan(_table())
        filt = TpuFilter(GreaterThan(_attr("a"), ec.Literal(3)), scan)
        proj = TpuProject([_attr("a")], filt)
        lim = TpuLocalLimit(5, proj)
        assert C.classify(scan) == C.BOUNDARY
        assert C.classify(filt) == C.PROGRAM
        assert C.classify(proj) == C.PROGRAM
        assert C.classify(lim) == C.PROGRAM
        assert C.classify(TpuBroadcastExchange(scan)) == C.BOUNDARY
        assert C.classify(_OpaqueExec(scan)) == C.BOUNDARY

    def test_lower_region_and_barrier_count(self):
        plan = _chain(3)
        members = [plan, plan.children[0], plan.children[0].children[0]]
        lowering = C.lower_region(members)
        assert [s for _n, s in lowering] == [C.PROGRAM] * 3
        assert C.barrier_count(lowering) == 0


# ---------------------------------------------------------------------------
# carving
# ---------------------------------------------------------------------------

class TestCarve:
    def test_wraps_member_region(self):
        conf = TpuConf({})
        carved = C.carve_plan(_chain(3), conf)
        assert isinstance(carved, TpuSuperstage)
        assert len(carved.members) == 3          # 2 projects + filter
        assert all(getattr(m, "_superstage", False)
                   for m in carved.members)
        # root consumer is the collect sink -> no edge resolve needed
        assert carved.resolve_output is False
        assert verify_plan(carved, passes=[STAGE]).ok

    def test_min_ops_threshold(self):
        conf = TpuConf({"spark.rapids.tpu.sql.superstage.minOps": 99})
        carved = C.carve_plan(_chain(3), conf)
        assert not isinstance(carved, TpuSuperstage)

    def test_opaque_node_ejected_and_regions_split(self):
        # Project over Opaque over (Project, Filter): the opaque node
        # stays on its own dispatch; the region below it still carves
        top = TpuProject([_attr("a"), _attr("b", T.FLOAT64)],
                         TpuLocalLimit(8, _OpaqueExec(_chain(2))))
        from spark_rapids_tpu.obs.registry import COMPILE_SUPERSTAGES
        before = COMPILE_SUPERSTAGES.labels(event="ejected").value
        carved = C.carve_plan(top, TpuConf({}))
        after = COMPILE_SUPERSTAGES.labels(event="ejected").value
        assert after == before + 1
        assert isinstance(carved, TpuSuperstage)          # {proj, limit}
        opaque = carved.children[0].children[0].children[0]
        assert isinstance(opaque, _OpaqueExec)
        assert isinstance(opaque.children[0], TpuSuperstage)  # below
        report = verify_plan(carved, passes=[STAGE])
        assert report.ok, report.violations

    def test_unsafe_consumer_gets_edge_resolve(self):
        # a region whose parent is an unknown boundary must verify its
        # own speculative output at the stage edge
        top = _OpaqueExec(_chain(2))
        carved = C.carve_plan(top, TpuConf({}))
        inner = carved.children[0]
        assert isinstance(inner, TpuSuperstage)
        assert inner.resolve_output is True

    def test_planner_carves_only_when_enabled(self):
        def phys_for(conf_extra):
            def fn(s):
                df = s.create_dataframe(_table(), num_partitions=1)
                df.collect()
                return s.last_physical_plan
            return with_tpu_session(fn, conf_extra)

        on = phys_for({})
        off = phys_for({"spark.rapids.tpu.sql.superstage": False})

        def has_stage(node):
            return isinstance(node, TpuSuperstage) or \
                any(has_stage(c) for c in node.children)
        assert not has_stage(off)
        # a bare scan-collect may be below min-ops (whole-stage fusion
        # folds filter+project into ONE staged member); adding a limit
        # gives the region a second member and it carves
        def shaped(s):
            from spark_rapids_tpu.api import functions as F
            df = s.create_dataframe(_table(), num_partitions=1)
            df = df.filter(F.col("a") > 3).select(
                F.col("a"), (F.col("b") * 2.0).alias("b2")).limit(4)
            df.collect()
            return s.last_physical_plan
        assert has_stage(with_tpu_session(shaped, {}))
        assert on is not None


# ---------------------------------------------------------------------------
# PV-STAGE verifier pass
# ---------------------------------------------------------------------------

class TestStageVerifier:
    def test_boundary_member_violation(self):
        scan = TpuLocalScan(_table())
        bad = TpuSuperstage(scan, [scan], C.lower_region([scan]))
        report = verify_plan(bad, passes=[STAGE])
        assert any("boundary class" in v.message
                   for v in report.violations)

    def test_flag_outside_region_violation(self):
        plan = _chain(2)
        plan._superstage = True      # armed but never carved
        report = verify_plan(plan, passes=[STAGE])
        assert any("outside any carved region" in v.message
                   for v in report.violations)

    def test_multi_barrier_violation(self):
        plan = _chain(2)
        members = [plan, plan.children[0]]
        stage = TpuSuperstage(plan, members,
                              [("A", C.BARRIER), ("B", C.BARRIER)])
        for m in members:
            m._superstage = True
        report = verify_plan(stage, passes=[STAGE])
        assert any("flush barriers" in v.message
                   for v in report.violations)

    def test_wrong_root_violation(self):
        plan = _chain(2)
        other = _chain(2)
        stage = TpuSuperstage(plan, [other], C.lower_region([other]))
        other._superstage = True
        report = verify_plan(stage, passes=[STAGE])
        assert any("wrapper's child" in v.message
                   for v in report.violations)

    def test_full_default_pass_set_on_carved_plan(self):
        carved = C.carve_plan(_chain(3), TpuConf({}))
        report = verify_plan(carved)        # all five passes
        assert report.ok, report.violations


# ---------------------------------------------------------------------------
# determinism: bit-identical on/off, across the parallelism matrix
# ---------------------------------------------------------------------------

def _bench_shape_df(s, n_rows=60_000, parts=4):
    from spark_rapids_tpu.api import functions as F
    rng = np.random.default_rng(7)
    df = s.create_dataframe({
        "k": rng.integers(0, 1000, n_rows).astype(np.int64),
        "a": rng.integers(-100_000, 100_000, n_rows).astype(np.int64),
        "x": rng.random(n_rows),
        "y": rng.random(n_rows),
    }, num_partitions=parts)
    dim = s.create_dataframe({
        "dk": np.arange(1000, dtype=np.int64),
        "w": rng.random(1000),
    }, num_partitions=1)
    agg = (df.filter((F.col("x") > 0.1) & (F.col("a") % 7 != 0))
             .with_column("z", F.col("x") * F.col("y") + F.col("a"))
             .group_by("k")
             .agg(F.sum("z").alias("sz"), F.count().alias("c"),
                  F.max("x").alias("mx")))
    return (agg.join(dim, agg["k"] == dim["dk"], "inner")
               .select(F.col("k"), F.col("sz"), F.col("c"),
                       (F.col("mx") * F.col("w")).alias("mw")))


def _ipc_hash(table: pa.Table) -> str:
    table = table.combine_chunks()
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return hashlib.sha256(sink.getvalue().to_pybytes()).hexdigest()


def test_bench_shape_identical_across_superstage_and_parallelism():
    hashes = {}
    for stage_on in (True, False):
        for par in (1, 4):
            conf = {"spark.rapids.tpu.sql.superstage": stage_on,
                    "spark.rapids.tpu.exec.pipelineParallelism": par,
                    "spark.rapids.tpu.exec.pipelinePrefetchDepth": par}
            tbl = with_tpu_session(
                lambda s: _bench_shape_df(s).to_arrow(), conf)
            hashes[(stage_on, par)] = _ipc_hash(tbl)
    assert len(set(hashes.values())) == 1, hashes


@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcds_compile") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


def _run_tpcds(tpcds_dir, query, conf):
    def fn(s):
        tpcds.register(s, tpcds_dir)
        rows = s.sql(tpcds.QUERIES[query]).collect()
        return rows, getattr(s, "last_query_flushes", None)
    return with_tpu_session(fn, conf)


@pytest.mark.parametrize("query", ["q3", "q42", "q52", "q96"])
def test_tpcds_identical_superstage_on_off(tpcds_dir, query):
    on_rows, on_flushes = _run_tpcds(tpcds_dir, query, {})
    off_rows, off_flushes = _run_tpcds(
        tpcds_dir, query, {"spark.rapids.tpu.sql.superstage": False})
    # exact row-for-row equality INCLUDING order
    assert on_rows == off_rows
    h_on = hashlib.sha256(repr(on_rows).encode()).hexdigest()
    h_off = hashlib.sha256(repr(off_rows).encode()).hexdigest()
    assert h_on == h_off
    assert on_flushes is not None and off_flushes is not None


def test_tpcds_q3_warm_flush_budget(tpcds_dir):
    # the acceptance criterion at test scale: a warm carved star-join
    # runs in at most 2 fused round trips, strictly fewer than uncarved
    def fn(s):
        tpcds.register(s, tpcds_dir)
        sql = tpcds.QUERIES["q3"]
        s.sql(sql).collect()               # warm (compile caches)
        f0 = pending.FLUSH_COUNT
        s.sql(sql).collect()
        return pending.FLUSH_COUNT - f0

    warm_on = with_tpu_session(fn, {})
    warm_off = with_tpu_session(
        fn, {"spark.rapids.tpu.sql.superstage": False})
    assert warm_on <= 2, f"carved warm q3 took {warm_on} flushes"
    assert warm_on < warm_off, (warm_on, warm_off)


def test_flushes_in_event_log(tmp_path):
    from spark_rapids_tpu.tools.events import read_event_log
    log = str(tmp_path / "events.jsonl")

    def fn(s):
        _bench_shape_df(s, n_rows=5_000, parts=2).to_arrow()
    with_tpu_session(fn, {"spark.rapids.tpu.eventLog.path": log})
    recs = read_event_log(log)
    assert recs and isinstance(recs[-1].get("flushes"), int)
    assert recs[-1]["flushes"] >= 1


# ---------------------------------------------------------------------------
# fallbacks
# ---------------------------------------------------------------------------

def test_duplicate_key_build_redoes_exactly():
    # build side holds duplicate keys -> the speculative unique-match
    # fit flag FAILS and the join must redo on the exact sized path,
    # matching the uncarved engine row-for-row
    def q(s):
        from spark_rapids_tpu.api import functions as F
        left = s.create_dataframe({
            "k": np.array([1, 2, 3, 4, 5, 2, 7, 8], np.int64),
            "v": np.arange(8, dtype=np.int64)}, num_partitions=1)
        right = s.create_dataframe({
            "rk": np.array([2, 2, 3, 3, 9], np.int64),
            "w": np.arange(5, dtype=np.int64)}, num_partitions=1)
        j = (left.join(right, left["k"] == right["rk"], "inner")
                 .select(F.col("k"), F.col("v"), F.col("w")))
        return sorted(map(tuple, j.collect()))

    on = with_tpu_session(q, {})
    off = with_tpu_session(q, {"spark.rapids.tpu.sql.superstage": False})
    assert on == off
    assert len(on) == 6                    # 2x(k=2 twice) + 2 for k=3


def test_stage_setup_failure_falls_back_eagerly():
    plan = _chain(2)
    carved = C.carve_plan(plan, TpuConf({}))
    assert isinstance(carved, TpuSuperstage)
    root = carved.children[0]
    orig_execute = root.execute
    calls = []

    def boom():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("trace failure")
        return orig_execute()

    root.execute = boom
    from spark_rapids_tpu.obs.registry import COMPILE_SUPERSTAGES
    before = COMPILE_SUPERSTAGES.labels(event="fallback").value
    parts = carved.execute()
    rows = sum(b.num_rows for p in parts for b in p)
    assert rows == 60                      # 64 rows, filter a > 3
    assert len(calls) == 2
    # the retry ran DISARMED: per-operator dispatch, flags stripped
    assert all(not getattr(m, "_superstage", False)
               for m in carved.members)
    assert COMPILE_SUPERSTAGES.labels(event="fallback").value == \
        before + 1


def test_cancel_unwinds_mid_superstage():
    # the per-batch timed region inside TpuSuperstage._drain is a
    # cancellation checkpoint: a token cancelled between batches must
    # unwind the drain with QueryCancelledError
    carved = C.carve_plan(_chain(2, parts=4), TpuConf({}))
    assert isinstance(carved, TpuSuperstage)
    token = CancelToken(query_id="stage-cancel")
    with query_context(token):
        parts = carved.execute()
        got = 0
        with pytest.raises(QueryCancelledError):
            for part in parts:
                for _b in part:
                    got += 1
                    token.cancel("test-cancel")
    assert got >= 1
