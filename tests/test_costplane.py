"""Device-compute cost plane tests (obs/costplane.py): static XLA
cost capture at every compile origin, the dispatch-ledger join into
per-program achieved rates and roofline verdicts, padding-waste
arithmetic, the doctor's exact device_compute sub-split, digest
stability across pipeline parallelism {1,4} x superstage on/off, the
REQUIRED_PROGRAMS coverage gate (mirroring the jaxpr auditor), the
measured-vs-static profile intensity cross-check, and the
zero-extra-flush + disabled-plane + lint-scope acceptance contracts.
"""
import json
import os

import jax
import numpy as np
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar import pending
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import costplane, doctor

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "lint_fixtures")


@pytest.fixture(scope="module", autouse=True)
def _seed_store():
    # capture runs ONCE per (program, bucket) for the life of the
    # process, but the engine's JIT caches stay warm across tests —
    # so seed the process-lifetime store while this module still owns
    # cold caches (a later reset() could never get the records back)
    costplane.configure(TpuConf({}))
    s = TpuSession(TpuConf({}))
    _agg_join_df(s).collect()
    yield


@pytest.fixture(autouse=True)
def _cost_guard():
    # snapshot/restore instead of reset(): unit tests may freely
    # reset or fill the bounded store without starving the e2e tests
    # that rely on the seeded process-lifetime records
    costplane.configure(TpuConf({}))
    with costplane._LOCK:
        saved = (dict(costplane._COSTS),
                 {k: list(v) for k, v in costplane._DISPATCH.items()},
                 dict(costplane._CAPTURES),
                 costplane._DROPPED, costplane._DISPATCH_DROPPED,
                 dict(costplane._LAST))
    yield
    costplane.configure(TpuConf({}))
    with costplane._LOCK:
        costplane._COSTS.clear()
        costplane._COSTS.update(saved[0])
        costplane._DISPATCH.clear()
        costplane._DISPATCH.update(saved[1])
        costplane._CAPTURES.clear()
        costplane._CAPTURES.update(saved[2])
        costplane._DROPPED = saved[3]
        costplane._DISPATCH_DROPPED = saved[4]
        costplane._LAST.clear()
        costplane._LAST.update(saved[5])


def _agg_join_df(sess, n=50_000, groups=31):
    df = sess.range(0, n, 1, 4)
    df = df.with_column("k", df["id"] % groups)
    dim = sess.range(0, groups, 1, 1).with_column("v", F.col("id") * 2)
    j = df.join(dim.with_column_renamed("id", "k2"),
                df["k"] == F.col("k2"), "inner")
    return j.group_by("k").agg(F.sum("v").alias("sv"))


def _jit_add():
    return jax.jit(lambda x: x + 1)


def _args(n=1024):
    return (np.zeros((n,), dtype=np.int64),), {}


# ---------------------------------------------------------------------------
# 1. static-cost capture
# ---------------------------------------------------------------------------

class TestCapture:
    def test_capture_stores_xla_record_at_bucket(self):
        costplane.reset()
        args, kwargs = _args(1024)
        assert costplane.capture("prog_a", _jit_add(), args, kwargs)
        costs = costplane.static_costs()
        rec = costs[("prog_a", 1024)]
        assert rec["source"] == costplane.SOURCE_XLA
        assert rec["flops"] > 0 and rec["bytes"] > 0
        assert rec["io_bytes"] > 0
        assert rec["origin"] == costplane.ORIGIN_MISS

    def test_capture_records_origin(self):
        costplane.reset()
        args, kwargs = _args(64)
        assert costplane.capture("prog_w", _jit_add(), args, kwargs,
                                 origin=costplane.ORIGIN_WARMUP)
        rec = costplane.static_costs()[("prog_w", 64)]
        assert rec["origin"] == costplane.ORIGIN_WARMUP

    def test_capture_returns_false_on_tracer_args(self):
        # the program auditor traces make_jaxpr THROUGH wrapped
        # callables: capture must defer (False), not store garbage
        costplane.reset()
        seen = []

        def probe(x):
            seen.append(costplane.capture(
                "prog_t", _jit_add(), (x,), {}))
            return x + 1
        jax.make_jaxpr(probe)(np.zeros((8,), dtype=np.int64))
        assert seen == [False]
        assert ("prog_t", 8) not in costplane.static_costs()

    def test_wrap_capture_fires_once_and_preserves_result(self):
        costplane.reset()
        fn = costplane.wrap_capture("prog_wrap", _jit_add())
        x = np.arange(16, dtype=np.int64)
        out = fn(x)
        np.testing.assert_array_equal(np.asarray(out), x + 1)
        fn(x)
        assert costplane.record_count() == 1

    def test_wrap_capture_retries_after_traced_first_call(self):
        # first call under make_jaxpr defers; the next REAL call must
        # still capture (the done flag is only set on success)
        costplane.reset()
        fn = costplane.wrap_capture("prog_retry", _jit_add())
        jax.make_jaxpr(lambda x: fn(x))(np.zeros((8,), dtype=np.int64))
        assert ("prog_retry", 8) not in costplane.static_costs()
        fn(np.zeros((8,), dtype=np.int64))
        assert ("prog_retry", 8) in costplane.static_costs()

    def test_static_fallback_upgrades_to_xla(self):
        costplane.reset()

        class _NoLower:
            pass
        assert costplane.capture("prog_up", _NoLower(), *(_args(32)))
        assert costplane.static_costs()[("prog_up", 32)]["source"] \
            == costplane.SOURCE_STATIC
        assert costplane.capture("prog_up", _jit_add(), *(_args(32)))
        assert costplane.static_costs()[("prog_up", 32)]["source"] \
            == costplane.SOURCE_XLA

    def test_store_is_bounded_and_counts_drops(self):
        costplane.reset()
        limit = costplane._MAX_RECORDS
        for i in range(limit + 5):
            costplane.capture(f"prog_{i}", _jit_add(), *(_args(16)))
        assert costplane.record_count() == limit
        assert costplane.dropped_count() == 5


# ---------------------------------------------------------------------------
# 2. dispatch join + roofline model
# ---------------------------------------------------------------------------

class TestRoofline:
    def test_ridge_is_peak_ratio(self):
        assert costplane.ridge_intensity() == pytest.approx(
            costplane._PEAK_FLOPS / costplane._PEAK_BYTES)

    def test_verdict_boundary_at_ridge(self):
        ridge = costplane.ridge_intensity()
        byts = float(2 ** 20)       # power of two: ridge*b/b is exact
        assert costplane.roofline_verdict(ridge * byts, byts) \
            == costplane.VERDICT_COMPUTE
        assert costplane.roofline_verdict(ridge * byts * 0.999, byts) \
            == costplane.VERDICT_MEMORY

    def test_summary_joins_costs_with_window_dispatches(self):
        costplane.reset()
        costplane.capture("prog_j", _jit_add(), *(_args(1024)))
        marker = costplane.begin_query()
        costplane.note_dispatch("prog_j", 1024, rows=512)
        costplane.note_dispatch("prog_j", 1024, rows=512)
        out = costplane.query_summary(marker, busy_ms=0.01)
        (e,) = out["programs"]
        assert e["program"] == "prog_j" and e["bucket"] == 1024
        assert e["dispatches"] == 2 and e["source"] == "xla"
        assert e["est_share_pct"] == pytest.approx(100.0)
        # published rates round to 3 decimals, hence the abs tolerance
        assert e["achieved_gflops"] == pytest.approx(
            e["flops"] * 2 / 1e-5 / 1e9, abs=1e-3)
        assert out["verdict"] == e["verdict"]
        assert out["compute_share_pct"] + out["memory_share_pct"] \
            == pytest.approx(100.0, abs=1e-9)

    def test_busy_apportioned_by_dispatch_weighted_t_est(self):
        costplane.reset()
        costplane.capture("prog_small", _jit_add(), *(_args(64)))
        costplane.capture("prog_big", _jit_add(), *(_args(65536)))
        marker = costplane.begin_query()
        costplane.note_dispatch("prog_small", 64)
        costplane.note_dispatch("prog_big", 65536)
        out = costplane.query_summary(marker, busy_ms=100.0)
        by = {e["program"]: e for e in out["programs"]}
        # the big program's t_est dominates, so it owns more busy share
        assert by["prog_big"]["est_share_pct"] > \
            by["prog_small"]["est_share_pct"]
        assert sum(e["est_share_pct"] for e in out["programs"]) \
            == pytest.approx(100.0, abs=0.01)

    def test_uncosted_dispatches_are_counted_not_invented(self):
        costplane.reset()
        marker = costplane.begin_query()
        costplane.note_dispatch("prog_mystery", 2048)
        out = costplane.query_summary(marker, busy_ms=5.0)
        assert out["uncosted_dispatches"] == 1
        (e,) = out["programs"]
        assert e["flops"] is None and e["verdict"] is None
        assert out["verdict"] is None

    def test_summary_windows_are_disjoint(self):
        costplane.reset()
        costplane.capture("prog_win", _jit_add(), *(_args(128)))
        m1 = costplane.begin_query()
        costplane.note_dispatch("prog_win", 128, rows=100)
        costplane.query_summary(m1, busy_ms=1.0)
        m2 = costplane.begin_query()
        out2 = costplane.query_summary(m2, busy_ms=1.0)
        assert out2["programs"] == []


# ---------------------------------------------------------------------------
# 3. padding-waste arithmetic
# ---------------------------------------------------------------------------

class TestPaddingWaste:
    def test_waste_is_exact_over_rows_known_dispatches(self):
        costplane.reset()
        costplane.capture("prog_p", _jit_add(), *(_args(1024)))
        marker = costplane.begin_query()
        costplane.note_dispatch("prog_p", 1024, rows=512)
        costplane.note_dispatch("prog_p", 1024, rows=256)
        out = costplane.query_summary(marker, busy_ms=4.0)
        # (512 + 256) effective rows over 2 x 1024 padded capacity
        (e,) = out["programs"]
        assert e["padding_waste_pct"] == pytest.approx(62.5)
        assert out["padding_waste_pct"] == pytest.approx(62.5)

    def test_waste_none_when_rows_unknown(self):
        costplane.reset()
        costplane.capture("prog_u", _jit_add(), *(_args(512)))
        marker = costplane.begin_query()
        costplane.note_dispatch("prog_u", 512)        # rows unknowable
        out = costplane.query_summary(marker, busy_ms=4.0)
        (e,) = out["programs"]
        assert e["padding_waste_pct"] is None
        assert out["padding_waste_pct"] is None

    def test_rows_if_resolved_never_flushes(self):
        class _Lazy:
            _val = None
            _staged = None
        class _B:
            rows_lazy = _Lazy()
        assert costplane.rows_if_resolved(_B()) is None
        class _B2:
            rows_lazy = 37
        assert costplane.rows_if_resolved(_B2()) == 37


# ---------------------------------------------------------------------------
# 4. doctor sub-verdict decomposition
# ---------------------------------------------------------------------------

class TestDoctorBreakdown:
    def _cp(self, comp, mem, waste):
        return {"costed_records": 3, "compute_share_pct": comp,
                "memory_share_pct": mem, "padding_waste_pct": waste}

    def test_breakdown_sums_exactly_to_share(self):
        for share in (25.235, 12.697, 99.999, 0.001):
            sub = doctor._device_compute_breakdown(
                share, self._cp(37.5, 62.5, 26.718))
            assert sum(sub.values()) == pytest.approx(
                round(share, 3), abs=1e-12), (share, sub)

    def test_breakdown_padding_then_roofline_split(self):
        sub = doctor._device_compute_breakdown(
            50.0, self._cp(60.0, 40.0, 20.0))
        assert sub["padding_waste"] == pytest.approx(10.0)
        assert sub["compute_bound"] == pytest.approx(24.0)
        assert sub["memory_bound"] == pytest.approx(16.0)

    def test_breakdown_absent_without_costplane(self):
        assert doctor._device_compute_breakdown(40.0, None) is None
        assert doctor._device_compute_breakdown(
            40.0, {"costed_records": 0}) is None

    def test_diagnose_attaches_breakdown_and_evidence(self):
        from spark_rapids_tpu.obs.registry import TIMELINE_GAP_CAUSES
        gaps = {c: 0.0 for c in TIMELINE_GAP_CAUSES}
        gaps["host_staging"] = 60.0
        tl = {"busy_ms": 40.0, "window_ms": 100.0, "util_pct": 40.0,
              "gaps": gaps}
        cp = dict(self._cp(0.0, 100.0, 25.0), verdict="memory_bound",
                  achieved_gflops=81.2, achieved_gbps=15.7)
        d = doctor.diagnose(tl, costplane=cp)
        sub = d.data["device_compute_breakdown"]
        assert sum(sub.values()) == pytest.approx(
            d.data["shares"]["device_compute"], abs=1e-12)
        (ev,) = [c["evidence"] for c in d.headroom
                 if c["cause"] == "device_compute"]
        assert "roofline[memory_bound" in ev
        assert "padding_waste=25.0%" in ev

    def test_diagnose_without_costplane_keeps_old_shape(self):
        from spark_rapids_tpu.obs.registry import TIMELINE_GAP_CAUSES
        gaps = {c: 0.0 for c in TIMELINE_GAP_CAUSES}
        tl = {"busy_ms": 40.0, "window_ms": 100.0, "util_pct": 100.0,
              "gaps": gaps}
        d = doctor.diagnose(tl)
        assert "device_compute_breakdown" not in d.data


# ---------------------------------------------------------------------------
# 5. coverage: every REQUIRED_PROGRAMS member costable (auditor mirror)
# ---------------------------------------------------------------------------

class TestCoverage:
    def test_every_required_program_captures_a_static_cost(self):
        from spark_rapids_tpu.analysis import program_audit as PA
        costplane.reset()
        specs = {s.name: s for s in PA.collect_specs()}
        assert set(specs) >= set(PA.REQUIRED_PROGRAMS)
        for name in sorted(PA.REQUIRED_PROGRAMS):
            fn, args, kwargs = specs[name].build()
            jfn = fn if hasattr(fn, "lower") else jax.jit(fn, **kwargs)
            assert costplane.capture(name, jfn, args, {}), name
        assert costplane.coverage_gaps() == [], costplane.coverage_gaps()
        assert set(costplane.costed_programs()) \
            >= set(PA.REQUIRED_PROGRAMS)

    def test_quartet_covers_trace_cache_names(self):
        # the end-to-end path (seeded by the module fixture): the
        # shared hash_aggregate trace cache covers all three
        # auditor-named aggregate variants
        costed = set(costplane.costed_programs())
        assert {"fused_project", "hash_aggregate_grouped",
                "hash_aggregate_whole_stage",
                "hash_aggregate_global"} <= costed, costed


# ---------------------------------------------------------------------------
# 6. measured-vs-static profile intensity cross-check
# ---------------------------------------------------------------------------

class TestMeasuredIntensity:
    def test_measured_ranks_agree_with_static_partial_order(self):
        from spark_rapids_tpu.obs import profile
        measured = {c: costplane.measured_intensity(c)
                    for c in ("project", "join", "aggregate",
                              "exchange")}
        static = {c: next(f for k, f in profile._INTENSITY if k in c)
                  for c in ("project", "join", "aggregate", "exchange")}
        assert all(v is not None and v > 0 for v in measured.values())
        # the baseline class IS the normalization anchor
        assert measured["project"] == pytest.approx(1.0)
        # both tables rank heavy relational classes above the
        # project baseline and the exchange sketch above it too
        for table in (measured, static):
            assert table["join"] > table["project"]
            assert table["aggregate"] > table["exchange"] \
                > table["project"]

    def test_profile_intensity_prefers_measured_then_falls_back(self):
        from spark_rapids_tpu.obs import profile
        assert profile._intensity("aggregate") == pytest.approx(
            costplane.measured_intensity("aggregate"))
        # classes with no live capture still use the static factors
        assert costplane.measured_intensity("sort") is None
        assert profile._intensity("sort") == 8.0
        assert profile._intensity("unknown_operator") == 2.0


# ---------------------------------------------------------------------------
# 7. end-to-end acceptance contracts
# ---------------------------------------------------------------------------

class TestEndToEnd:
    def test_session_surfaces_costplane(self):
        s = TpuSession(TpuConf({}))
        df = _agg_join_df(s)
        df.collect()
        df.collect()
        cost = s.last_query_costplane
        assert cost is not None and cost["costed_records"] > 0
        assert cost["programs"]
        assert all(e["source"] == "xla" for e in cost["programs"]
                   if e["flops"] is not None)
        assert cost["verdict"] in (costplane.VERDICT_COMPUTE,
                                   costplane.VERDICT_MEMORY)
        assert cost["compute_share_pct"] + cost["memory_share_pct"] \
            == pytest.approx(100.0, abs=1e-6)
        assert (cost["padding_waste_pct"] or 0) > 0
        sub = s.last_query_diagnosis.data["device_compute_breakdown"]
        assert sum(sub.values()) == pytest.approx(
            s.last_query_diagnosis.data["shares"]["device_compute"],
            abs=1e-12)

    def test_costplane_adds_zero_flushes(self):
        def measure(enabled):
            s = TpuSession(TpuConf({
                "spark.rapids.tpu.obs.cost.enabled": enabled}))
            df = _agg_join_df(s)
            df.collect()                       # warm
            f0 = pending.FLUSH_COUNT
            df.collect()
            return pending.FLUSH_COUNT - f0, s.last_query_costplane
        flushes_on, cost_on = measure(True)
        flushes_off, cost_off = measure(False)
        assert cost_on is not None and cost_off is None
        # the acceptance contract: an EXACT device round-trip match
        assert flushes_on == flushes_off

    def test_digest_stable_across_parallelism_and_superstage(self):
        digests = {}
        for par in (1, 4):
            for stage in (True, False):
                s = TpuSession(TpuConf({
                    "spark.rapids.tpu.exec.pipelineParallelism": par,
                    "spark.rapids.tpu.sql.superstage": stage}))
                df = _agg_join_df(s)
                df.collect()
                df.collect()
                cost = s.last_query_costplane
                assert cost is not None
                assert cost["compute_share_pct"] \
                    + cost["memory_share_pct"] == pytest.approx(
                        100.0, abs=1e-6)
                digests[(par, stage)] = cost["digest"]
        # model-only digest: execution config must not move it
        assert len(set(digests.values())) == 1, digests

    def test_disabled_plane_is_a_noop(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        costplane.reset()
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.obs.cost.enabled": False}))
        _agg_join_df(s).collect()
        assert s.last_query_costplane is None
        with open(log) as f:
            recs = [json.loads(line) for line in f]
        assert all("costplane" not in r for r in recs)

    def test_conf_overrides_peaks_and_bound(self):
        costplane.configure(TpuConf({
            "spark.rapids.tpu.obs.cost.peakTeraflops": 100.0,
            "spark.rapids.tpu.obs.cost.peakHbmGBps": 500.0,
            "spark.rapids.tpu.obs.cost.maxRecords": 4}))
        try:
            assert costplane.ridge_intensity() == pytest.approx(
                100.0e12 / 500.0e9)
            costplane.reset()       # guard fixture restores the store
            for i in range(6):
                costplane.capture(f"prog_{i}", _jit_add(), *(_args(16)))
            assert costplane.record_count() == 4
            assert costplane.dropped_count() == 2
        finally:
            costplane.configure(TpuConf({}))


# ---------------------------------------------------------------------------
# 8. surfaces: event log, Prometheus, stats, report
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_event_log_record_carries_costplane(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        _agg_join_df(s).collect()
        with open(log) as f:
            (rec,) = [json.loads(line) for line in f]
        cost = rec["costplane"]
        assert cost["costed_records"] > 0 and cost["programs"]
        assert rec["doctor"]["device_compute_breakdown"]

    def test_prometheus_families_present(self):
        from spark_rapids_tpu.obs.prom import render_text
        s = TpuSession(TpuConf({}))
        _agg_join_df(s).collect()
        text = render_text()
        for fam in ("tpu_cost_records", "tpu_cost_records_dropped",
                    "tpu_cost_padding_waste_pct",
                    "tpu_cost_captures_total",
                    "tpu_cost_roofline_verdicts_total",
                    "tpu_cost_achieved_gflops",
                    "tpu_cost_achieved_gbps"):
            assert fam in text, fam

    def test_stats_section_shape(self):
        costplane.reset()
        sec = costplane.stats_section()
        assert sec["enabled"] is True
        assert sec["records"] == 0
        assert set(sec["captures"]) == {"xla", "static", "skipped"}
        assert sec["ridge_intensity"] > 0
        assert sec["digest"] == costplane.stable_digest()

    def test_report_cost_section_renders(self, tmp_path, capsys):
        from spark_rapids_tpu.tools import report
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        _agg_join_df(s).collect()
        rc = report.main([log, "--cost"])
        out = capsys.readouterr().out
        assert rc in (0, None)
        assert "device-compute cost (roofline)" in out
        assert "padding waste" in out
        assert "doctor device_compute=" in out

    def test_report_cost_placeholder_on_pre_r14_record(self):
        from spark_rapids_tpu.tools.report import cost_lines
        (line,) = cost_lines({"query_id": "old"})
        assert "no costplane recorded" in line

    def test_report_all_flag_turns_every_section_on(self, tmp_path,
                                                    capsys):
        from spark_rapids_tpu.tools import report
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        _agg_join_df(s).collect()
        rc = report.main([log, "--all"])
        out = capsys.readouterr().out
        assert rc in (0, None)
        assert "device-compute cost (roofline)" in out
        assert "HBM memory (memplane)" in out
        assert "query doctor (cross-plane verdict)" in out
        assert "shuffle transport (netplane)" in out


# ---------------------------------------------------------------------------
# 9. lint scope: the plane's own file obeys the hot-path rules
# ---------------------------------------------------------------------------

class TestLintScope:
    def test_costplane_in_all_three_scopes(self):
        from spark_rapids_tpu.analysis import lint as AL
        rel = "spark_rapids_tpu/obs/costplane.py"
        scopes = AL._scopes_for(rel)
        assert {AL.SYNC001, AL.OBS002, AL.HYG002} <= scopes

    def test_seeded_fixture_trips_all_three_rules(self):
        from spark_rapids_tpu.analysis import lint as AL
        path = os.path.join(FIXTURES, "costplane_sync.py")
        with open(path) as f:
            findings = AL.lint_source(f.read(), path)
        rules = [f.rule for f in findings]
        assert rules.count(AL.SYNC001) >= 3
        assert AL.OBS002 in rules
        assert AL.HYG002 in rules

    def test_shipped_module_lints_clean(self):
        from spark_rapids_tpu.analysis import lint as AL
        rel = "spark_rapids_tpu/obs/costplane.py"
        path = os.path.join(REPO_ROOT, rel)
        with open(path) as f:
            findings = AL.lint_source(f.read(), rel,
                                      scopes=AL._scopes_for(rel))
        assert findings == [], AL.format_findings(findings)
