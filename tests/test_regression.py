"""Performance regression sentinel tests (analysis/regression.py +
ci/perf_gate.py): the dual-shape bench-record parser, the longitudinal
ledger over the REAL in-repo BENCH_r*.json files (placeholder rows for
the r01-r05 key gaps, no crash), the committed PERF_BASELINE.json's
consistency with the round that seeded it, noise-aware compare
semantics (regression / improvement / exact / skipped), the seeded
perf-gate fixtures (a -20% record must trip the gate, a +50% record
must pass and suggest a baseline bump), and the lint-scope extension
over the two new modules."""
import importlib.util
import json
import os

import pytest

from spark_rapids_tpu.analysis import lint as AL
from spark_rapids_tpu.analysis import regression as R

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")
BASELINE = os.path.join(REPO_ROOT, "PERF_BASELINE.json")


def _gate():
    spec = importlib.util.spec_from_file_location(
        "ci_perf_gate", os.path.join(REPO_ROOT, "ci", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# 1. dual-shape parser
# ---------------------------------------------------------------------------

class TestParser:
    def test_wrapper_shape(self):
        rec = R.parse_record({"n": 9, "cmd": "python bench.py", "rc": 0,
                              "tail": "...", "parsed": {"value": 1.5}})
        assert rec == {"value": 1.5}

    def test_bare_shape(self):
        assert R.parse_record({"value": 2.0, "flushes": 2}) == \
            {"value": 2.0, "flushes": 2}

    def test_wrapper_without_parsed_falls_back_to_tail(self):
        tail = ('warmup noise\n{"value": 3.25, "flushes": 2}\n')
        rec = R.parse_record({"n": 7, "cmd": "x", "rc": 0, "tail": tail})
        assert rec == {"value": 3.25, "flushes": 2}

    def test_garbage_returns_none_not_raise(self):
        assert R.parse_record(None) is None
        assert R.parse_record("not json") is None
        assert R.parse_record(42) is None
        assert R.parse_record({"cmd": "x", "rc": 1, "tail": "boom"}) \
            is None


# ---------------------------------------------------------------------------
# 2. longitudinal ledger over the REAL in-repo files
# ---------------------------------------------------------------------------

class TestHistory:
    def test_loads_every_committed_round_sorted(self):
        rounds = R.load_history(REPO_ROOT)
        ns = [r.round for r in rounds]
        assert ns == sorted(ns)
        assert 1 in ns and 5 in ns and 11 in ns and 12 in ns
        # r06-r10 were never recorded: absent, not crashing
        assert not any(n in ns for n in (6, 7, 8, 9, 10))

    def test_early_rounds_degrade_to_placeholders(self):
        rounds = {r.round: r for r in R.load_history(REPO_ROOT)}
        r01 = rounds[1]
        # pre-r06 rounds lack every post-r05 key: .get degrades to
        # None placeholders, never KeyError
        for key in ("flushes", "device_util_pct", "util_gap_breakdown",
                    "host_drop_tax_ms", "peak_device_bytes"):
            assert r01.get(key) is None, key
        assert r01.get("value") is not None
        # the newest round carries the full gated key set (the four
        # cold-path keys exist only from r13 on, the three roofline
        # keys from r14, the three fleet keys from r15, the four
        # plan-cache/scheduler keys from r16, the obs-tax key from
        # r17, the residency key from r18, the six soak keys from
        # r19)
        newest = rounds[max(rounds)]
        for key, _d, _b in R.GATE_KEYS:
            assert newest.get(key) is not None, key

    def test_history_table_has_placeholder_rows(self):
        rounds = R.load_history(REPO_ROOT)
        table = R.history_table(rounds, keys=["value", "flushes"])
        assert len(table) == len(rounds)
        by_round = {row["round"]: row for row in table}
        assert by_round[1]["flushes"] is None      # placeholder
        assert by_round[12]["flushes"] is not None
        # every row has every requested column
        assert all(set(row) == {"round", "value", "flushes"}
                   for row in table)


# ---------------------------------------------------------------------------
# 3. baseline + compare semantics
# ---------------------------------------------------------------------------

class TestCompare:
    BASE = {"version": 1, "round": 12, "keys": {
        "value": {"value": 2.0, "direction": "higher", "band_pct": 30.0},
        "spill_ms": {"value": 10.0, "direction": "lower",
                     "band_pct": 50.0},
        "flushes": {"value": 2, "direction": "exact"},
    }}

    def test_within_band_ok(self):
        deltas = R.compare({"value": 1.8, "spill_ms": 12.0,
                            "flushes": 2}, self.BASE)
        assert all(d.status == "ok" for d in deltas)

    def test_regression_each_direction(self):
        deltas = {d.key: d for d in R.compare(
            {"value": 1.2, "spill_ms": 16.0, "flushes": 3}, self.BASE)}
        assert deltas["value"].status == "regression"       # -40%
        assert deltas["spill_ms"].status == "regression"    # +60%
        assert deltas["flushes"].status == "regression"     # exact
        assert R.regressions(list(deltas.values()))

    def test_improvement_each_direction(self):
        deltas = {d.key: d for d in R.compare(
            {"value": 3.0, "spill_ms": 2.0, "flushes": 2}, self.BASE)}
        assert deltas["value"].status == "improvement"
        assert deltas["spill_ms"].status == "improvement"
        assert deltas["flushes"].status == "ok"   # exact never improves

    def test_missing_key_skipped_not_failed(self):
        deltas = {d.key: d for d in R.compare({"value": 2.0}, self.BASE)}
        assert deltas["spill_ms"].status == "skipped"
        assert deltas["flushes"].status == "skipped"
        assert not R.regressions(list(deltas.values()))

    def test_zero_baseline_tax_respects_abs_floor(self):
        # a tax that measured 0.0 in the baseline round would gate at
        # 0*(1+band) == 0 without the floor: any jitter would fail CI
        base = {"version": 1, "round": 12, "keys": {
            "spill_ms": {"value": 0.0, "direction": "lower",
                         "band_pct": 150.0, "abs_floor": 5.0}}}
        ok = R.compare({"spill_ms": 3.0}, base)[0]
        bad = R.compare({"spill_ms": 7.5}, base)[0]
        assert ok.status == "ok"
        assert bad.status == "regression"
        # make_baseline seeds the floor for every lower-direction key
        seeded = R.make_baseline({"spill_ms": 0.0}, round_n=12)
        assert seeded["keys"]["spill_ms"]["abs_floor"] == \
            R.ABS_FLOORS["spill_ms"]

    def test_seeded_record_scales_only_throughput(self):
        rec = R.seeded_record(self.BASE, 0.8)
        assert rec["value"] == pytest.approx(1.6)
        assert rec["spill_ms"] == 10.0          # tax key: untouched
        assert rec["flushes"] == 2              # exact key: untouched


# ---------------------------------------------------------------------------
# 4. the committed baseline matches the round that seeded it
# ---------------------------------------------------------------------------

class TestCommittedBaseline:
    def test_baseline_values_equal_r19(self):
        base = R.load_baseline(BASELINE)
        assert base["round"] == 19
        r19 = R.load_round(os.path.join(REPO_ROOT,
                                        "BENCH_r19.json")).keys
        for key, spec in base["keys"].items():
            assert spec["value"] == r19[key], key
        # so the committed pair passes the gate by construction
        assert not R.regressions(R.compare(r19, base))

    def test_residency_key_gated_exact_at_zero(self):
        # r18's contract: a change that reintroduces a hidden
        # device->host sync (any nonzero undeclared_transfers) must
        # fail the gate, not a profiling session
        base = R.load_baseline(BASELINE)
        spec = base["keys"]["undeclared_transfers"]
        assert spec["direction"] == "exact"
        assert spec["value"] == 0
        dirty = dict(R.load_round(os.path.join(
            REPO_ROOT, "BENCH_r19.json")).keys)
        dirty["undeclared_transfers"] = 1
        bad = [d.key for d in R.regressions(R.compare(dirty, base))]
        assert bad == ["undeclared_transfers"], bad

    def test_leak_drift_key_gated_exact_at_zero(self):
        # r19's contract: the soak leak-drift monitor reading ANY
        # nonzero byte drift over the measured window must fail the
        # gate — a leak is never inside a noise band
        base = R.load_baseline(BASELINE)
        spec = base["keys"]["leak_drift_bytes"]
        assert spec["direction"] == "exact"
        assert spec["value"] == 0
        dirty = dict(R.load_round(os.path.join(
            REPO_ROOT, "BENCH_r19.json")).keys)
        dirty["leak_drift_bytes"] = 4096
        bad = [d.key for d in R.regressions(R.compare(dirty, base))]
        assert bad == ["leak_drift_bytes"], bad

    def test_true_r16_numbers_trip_only_the_r17_discontinuities(self):
        # the r17 obs-tax diet changed what two gated keys MEASURE:
        # device_util_pct's wall no longer contains the deferred
        # StatsProfile/doctor/history assembly (so util jumped from
        # ~52% to ~99%), and history_write_p99_us dropped ~10x when
        # the background writer stopped paying dumps+open per row.
        # The true r16 record must regress on exactly those two keys
        # against a baseline seeded from r17 — any third key tripping
        # means a band is too tight for real round-over-round noise.
        # (The committed baseline moved on to r18, so the r17 baseline
        # is reconstructed here with the same seeding path.)
        r16 = R.load_round(os.path.join(REPO_ROOT,
                                        "BENCH_r16.json")).keys
        r17 = R.load_round(os.path.join(REPO_ROOT,
                                        "BENCH_r17.json")).keys
        base17 = R.make_baseline(r17, round_n=17)
        bad = sorted(d.key
                     for d in R.regressions(R.compare(r16, base17)))
        assert bad == ["device_util_pct", "history_write_p99_us"], bad


# ---------------------------------------------------------------------------
# 5. the gate CLI + seeded fixtures
# ---------------------------------------------------------------------------

class TestGateCli:
    def test_seeded_regression_fixture_trips(self, capsys):
        rc = _gate().main(["--fixture", "regression"])
        out = capsys.readouterr().out
        assert rc == 1, out
        assert "PERF GATE: FAIL" in out
        # the doctor's verdict rides the failure: cause + roadmap item
        assert "doctor:" in out
        assert "primary bottleneck" in out
        assert "ROADMAP item" in out

    def test_seeded_improvement_fixture_passes_and_suggests_bump(
            self, capsys):
        rc = _gate().main(["--fixture", "improvement"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "PERF GATE: PASS" in out
        assert "baseline bump" in out

    def test_unknown_fixture_is_usage_error(self, capsys):
        assert _gate().main(["--fixture", "bogus"]) == 2

    def test_current_regressed_file_trips(self, tmp_path, capsys):
        base = R.load_baseline(BASELINE)
        rec = R.seeded_record(base, 0.7)
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"n": 99, "cmd": "x", "rc": 0,
                                 "tail": "", "parsed": rec}))
        rc = _gate().main(["--current", str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "regression" in out

    def test_seed_baseline_roundtrip(self, tmp_path, monkeypatch):
        gate = _gate()
        out_path = tmp_path / "PERF_BASELINE.json"
        monkeypatch.setattr(gate, "BASELINE_PATH", str(out_path))
        rc = gate._seed_baseline(
            os.path.join(REPO_ROOT, "BENCH_r19.json"))
        assert rc == 0
        reseeded = R.load_baseline(str(out_path))
        committed = R.load_baseline(BASELINE)
        assert reseeded["keys"] == committed["keys"]


# ---------------------------------------------------------------------------
# 6. lint scope extension + seeded fixture
# ---------------------------------------------------------------------------

class TestLintScopes:
    def test_new_modules_in_sync_obs_hyg_scopes(self):
        for rel in ("spark_rapids_tpu/obs/doctor.py",
                    "spark_rapids_tpu/analysis/regression.py"):
            scopes = AL._scopes_for(rel)
            assert AL.SYNC001 in scopes, rel
            assert AL.OBS002 in scopes, rel
            assert AL.HYG002 in scopes, rel

    def test_scoped_lint_fires_on_device_pull_in_doctor(self):
        src = ("import jax\n"
               "def corroborate(dev):\n"
               "    return jax.device_get(dev)\n")
        fs = AL.lint_source(
            src, "spark_rapids_tpu/obs/doctor.py",
            scopes=AL._scopes_for("spark_rapids_tpu/obs/doctor.py"))
        assert any(f.rule == AL.SYNC001 for f in fs)

    def test_seeded_doctor_fixture_trips_all_three_rules(self):
        path = os.path.join(FIXTURES, "doctor_sync.py")
        with open(path) as f:
            fs = AL.lint_source(f.read(), path)
        rules = {f.rule for f in fs}
        assert {AL.SYNC001, AL.OBS002, AL.HYG002} <= rules

    def test_shipped_modules_lint_clean(self):
        for rel in ("spark_rapids_tpu/obs/doctor.py",
                    "spark_rapids_tpu/analysis/regression.py"):
            path = os.path.join(REPO_ROOT, rel)
            with open(path) as f:
                fs = AL.lint_source(f.read(), rel,
                                    scopes=AL._scopes_for(rel))
            assert fs == [], AL.format_findings(fs)
