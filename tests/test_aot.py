"""AOT compile service tests (compile/aot.py + service/warmup.py).

Five surfaces:

1. Bucket-lattice unit contract — geometric growth, ratio validation,
   ``ratio=2`` reproducing the classic pow2 padding bit-for-bit.
2. Demand ledger + warmup registry — first-seen miss/hit derivation,
   warmup converting misses to hits, warmer variant bounding,
   candidate cross product, failure isolation.
3. Warmup attribution (the PR 13 bugfix regression) — a compile under
   an ACTIVE CancelToken but inside ``warmup_scope()`` lands on the
   ``warmup`` pseudo-victim: no ``inline_compile_ms`` on the token,
   excluded from the timeline's inline_compile evidence, segregated
   warmup_ns.
4. Persistence — manifest roundtrip, run-id discrimination,
   conf-fingerprint sensitivity, and the cross-process subprocess
   test: a child against a seeded cache dir records ZERO new compiles
   (tpu_compile_seconds untouched) while loading persistently.
5. Mask-correctness — bucketed execution (ratio 4) is sha-identical
   to unbucketed across pipelineParallelism {1,4} x superstage on/off.
"""
import hashlib
import json
import os
import subprocess
import sys
import time

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar import column
from spark_rapids_tpu.compile import aot
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import compile_watch, timeline
from spark_rapids_tpu.service.cancellation import CancelToken, \
    query_context
from spark_rapids_tpu.service.warmup import WarmupDaemon

MS = 1_000_000

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _aot_reset():
    """Isolate the process-wide AOT state (and the planes it feeds)."""
    aot.reset()
    compile_watch.reset()
    timeline.reset()
    yield
    aot.reset()
    compile_watch.reset()
    timeline.reset()
    default = TpuConf({})
    compile_watch.configure(default)
    timeline.configure(default)


# ---------------------------------------------------------------------------
# bucket lattice
# ---------------------------------------------------------------------------

class TestBucketLattice:
    def test_geometric_growth(self):
        lat = aot.BucketLattice(128, 4)
        assert lat.bucket(1) == 128
        assert lat.bucket(128) == 128
        assert lat.bucket(129) == 512
        assert lat.bucket(513) == 2048
        assert lat.points_up_to(600) == [128, 512, 2048]

    def test_ratio_two_reproduces_pow2_padding(self):
        lat = aot.BucketLattice(column.MIN_CAPACITY, 2)
        for n in (1, 7, 128, 129, 1000, 4096, 4097, 1 << 20):
            assert lat.bucket(n) == column.bucket_capacity(n), n

    @pytest.mark.parametrize("ratio", [0, 1, 3, 6, -2])
    def test_ratio_must_be_power_of_two(self, ratio):
        with pytest.raises(ValueError):
            aot.BucketLattice(128, ratio)

    def test_min_rows_validated(self):
        with pytest.raises(ValueError):
            aot.BucketLattice(0, 2)

    def test_configure_installs_column_hook(self):
        aot.configure(TpuConf(
            {"spark.rapids.tpu.compile.aot.bucketRatio": 4}))
        assert column.bucket_capacity(column.MIN_CAPACITY + 1) == \
            column.MIN_CAPACITY * 4
        aot.configure(TpuConf(
            {"spark.rapids.tpu.compile.aot.enabled": False}))
        assert column.bucket_capacity(column.MIN_CAPACITY + 1) == \
            column.MIN_CAPACITY * 2


# ---------------------------------------------------------------------------
# demand ledger
# ---------------------------------------------------------------------------

class TestDemandLedger:
    def setup_method(self):
        aot.configure(TpuConf({}))

    def test_first_demand_is_miss_then_hits(self):
        aot.note_demand("fused_project", 1024)
        aot.note_demand("fused_project", 1024)
        aot.note_demand("fused_project", 1024)
        snap = aot.demand_snapshot()
        assert snap["fused_project|1024"] == [2, 1]

    def test_distinct_buckets_miss_independently(self):
        aot.note_demand("fused_project", 1024)
        aot.note_demand("fused_project", 4096)
        snap = aot.demand_snapshot()
        assert snap["fused_project|1024"] == [0, 1]
        assert snap["fused_project|4096"] == [0, 1]
        assert aot.demanded_buckets() == [1024, 4096]

    def test_warmup_converts_future_miss_to_hit(self):
        aot.note_demand("staged_compute", 2048)   # discovers the bucket
        aot.register_warmer("fused_project", lambda b: None)
        assert aot.warm_missing(8) == 1
        aot.note_demand("fused_project", 2048)    # first tenant demand
        snap = aot.demand_snapshot()
        assert snap["fused_project|2048"] == [1, 0]   # hit, not miss

    def test_last_demand_is_per_cache_thread_local(self):
        aot.note_demand("fused_project", 1024)
        assert aot.last_demand("fused_project") == 1024
        assert aot.last_demand("staged_compute") is None

    def test_disabled_records_nothing(self):
        aot.configure(TpuConf(
            {"spark.rapids.tpu.compile.aot.enabled": False}))
        aot.note_demand("fused_project", 1024)
        assert aot.demand_snapshot() == {}


# ---------------------------------------------------------------------------
# warmup registry + daemon
# ---------------------------------------------------------------------------

class TestWarmupRegistry:
    def setup_method(self):
        aot.configure(TpuConf({}))

    def test_unknown_program_rejected(self):
        with pytest.raises(ValueError):
            aot.register_warmer("not_a_program", lambda b: None)

    def test_variants_bounded_oldest_evicted(self):
        for i in range(12):
            aot.register_warmer("fused_project", lambda b: None,
                                variant=f"v{i}")
        sec = aot.stats_section()
        assert sec["warmers"]["fused_project"] == 8
        aot.note_demand("fused_project", 1024)
        cands = aot.warm_candidates()
        variants = {v for (_p, v, _b) in cands}
        assert variants == {f"v{i}" for i in range(4, 12)}

    def test_candidates_are_cross_product_minus_warmed(self):
        aot.note_demand("fused_project", 1024)
        aot.note_demand("fused_project", 4096)
        aot.register_warmer("fused_project", lambda b: None)
        aot.register_warmer("staged_compute", lambda b: None)
        assert len(aot.warm_candidates()) == 4
        assert aot.warm_missing(2) == 2
        assert len(aot.warm_candidates()) == 2
        assert aot.warm_missing(8) == 2
        assert aot.warm_candidates() == []

    def test_failing_warmer_marked_and_counted_not_retried(self):
        calls = []

        def boom(bucket):
            calls.append(bucket)
            raise RuntimeError("warm failed")

        aot.note_demand("staged_compute", 1024)
        aot.register_warmer("staged_compute", boom)
        assert aot.warm_missing(8) == 0
        assert aot.warm_missing(8) == 0          # no retry storm
        assert calls == [1024]
        assert aot.stats_section()["warmup_failed"] == 1

    def test_daemon_sweeps_on_admission_signal(self):
        warmed = []
        aot.note_demand("fused_project", 1024)
        aot.register_warmer("fused_project", warmed.append)
        d = WarmupDaemon(interval_ms=5_000, max_per_cycle=4)
        d.start()
        try:
            d.note_admission("q-1")
            deadline = time.monotonic() + 5.0
            while not warmed and time.monotonic() < deadline:
                time.sleep(0.01)
            assert warmed == [1024]
            st = d.state()
            assert st["running"] and st["compiled"] == 1
            assert st["admissions_observed"] == 1
        finally:
            d.stop()
        assert not d.running()


# ---------------------------------------------------------------------------
# warmup attribution (the PR 13 bugfix)
# ---------------------------------------------------------------------------

class TestWarmupAttribution:
    def setup_method(self):
        aot.configure(TpuConf({}))

    def test_warmup_scope_outranks_active_cancel_token(self):
        """Regression: a first call under an ACTIVE CancelToken used to
        charge that query's inline_compile_ms even when the compile was
        a background warmup.  The warmup scope must win."""
        tok = CancelToken("q-victim")
        wrapped = compile_watch.wrap_miss(
            "fused_project", lambda: time.sleep(0.01), "sig")
        with query_context(tok):
            with aot.warmup_scope():
                wrapped()
        rec = compile_watch.records_since(0)[0]
        assert rec["origin"] == "warmup"
        assert not rec["inline"] and rec["query_id"] is None
        assert "inline_compile_ms" not in tok.observed
        assert compile_watch.inline_ns() == 0
        assert compile_watch.total_ns() == 0      # session deltas clean
        assert compile_watch.warmup_ns() > 0

    def test_inline_origin_without_warmup_scope(self):
        tok = CancelToken("q-inline")
        wrapped = compile_watch.wrap_miss(
            "fused_project", lambda: time.sleep(0.005), "sig")
        with query_context(tok):
            wrapped()
        rec = compile_watch.records_since(0)[0]
        assert rec["origin"] == "inline" and rec["inline"]
        assert tok.observed["inline_compile_ms"] > 0

    def test_compile_record_carries_demand_bucket(self):
        aot.note_demand("fused_project", 4096)
        compile_watch.note_compile("fused_project", 5 * MS, "sig")
        rec = compile_watch.records_since(0)[0]
        assert rec["bucket"] == 4096

    def test_timeline_classifies_warmup_window_as_idle(self):
        """A warmup compile's window is NOT inline_compile evidence:
        in a process summary the gap stays idle."""
        now = time.perf_counter_ns()
        t0 = now - 20 * MS
        timeline._INTERVALS.append((t0, t0 + 5 * MS))
        compile_watch._RECORDS.append({
            "cache": "ut", "dur_ms": 4.0, "signature": "",
            "inline": False, "origin": "warmup", "bucket": 1024,
            "query_id": None, "end_ns": t0 + 9 * MS})
        s = timeline._summarize(0, t0, now, is_query=False)
        assert s["gaps"]["inline_compile"] == 0.0
        assert s["gaps"]["idle"] == pytest.approx(75.0, abs=0.1)

    def test_timeline_pre_r13_record_still_compile_evidence(self):
        """Placeholder tolerance: records without an origin key (pre-r13
        event logs) keep classifying as compile evidence."""
        now = time.perf_counter_ns()
        t0 = now - 20 * MS
        timeline._INTERVALS.append((t0, t0 + 5 * MS))
        compile_watch._RECORDS.append({
            "cache": "ut", "dur_ms": 4.0, "signature": "",
            "inline": True, "query_id": None, "end_ns": t0 + 9 * MS})
        s = timeline._summarize(0, t0, now, is_query=True)
        assert s["gaps"]["inline_compile"] == pytest.approx(20.0, abs=0.1)


# ---------------------------------------------------------------------------
# persistence: manifest + fingerprint
# ---------------------------------------------------------------------------

class TestManifest:
    def test_roundtrip_and_run_discrimination(self, tmp_path,
                                              monkeypatch):
        conf = TpuConf({
            "spark.rapids.tpu.compile.aot.cacheDir": str(tmp_path),
            # keep the in-process jax compilation cache untouched
            # (conftest disables it on the CPU test mesh)
            "spark.rapids.tpu.compile.aot.xlaCache.enabled": False,
        })
        aot.configure(conf)
        key = aot.first_call_key("fused_project", "sig-a")
        assert key is not None
        aot.manifest_add(key, "fused_project", "sig-a", 1024, 12.5)
        assert aot.manifest_entries() == 1
        # same run -> never a persistent hit, even when wired
        monkeypatch.setattr(aot, "_XLA_CACHE_WIRED", True)
        assert not aot.persistent_ready(key)
        # simulate a later process: reload manifest under a fresh run id
        monkeypatch.setattr(aot, "_RUN_ID", "another-run")
        aot._load_manifest()
        assert aot.persistent_ready(key)
        # unwired XLA cache -> bookkeeping only, no persistent claims
        monkeypatch.setattr(aot, "_XLA_CACHE_WIRED", False)
        assert not aot.persistent_ready(key)

    def test_first_call_key_none_without_cache_dir(self):
        aot.configure(TpuConf({}))
        assert aot.first_call_key("fused_project", "sig") is None

    def test_wrap_miss_routes_persistent_hit(self, tmp_path,
                                             monkeypatch):
        aot.configure(TpuConf({
            "spark.rapids.tpu.compile.aot.cacheDir": str(tmp_path),
            "spark.rapids.tpu.compile.aot.xlaCache.enabled": False,
        }))
        key = aot.manifest_key("fused_project", "sig-p")
        aot.manifest_add(key, "fused_project", "sig-p", 1024, 3.0)
        monkeypatch.setattr(aot, "_XLA_CACHE_WIRED", True)
        monkeypatch.setattr(aot, "_RUN_ID", "later-run")
        aot._load_manifest()
        wrapped = compile_watch.wrap_miss(
            "fused_project", lambda: None, "sig-p")
        wrapped()
        assert compile_watch.persistent_hits() == 1
        assert compile_watch.total_ns() == 0     # no compile counted
        rec = compile_watch.records_since(0)[0]
        assert rec["origin"] == "persistent"

    def test_conf_fingerprint_sensitivity(self):
        fp_default = aot.conf_fingerprint(TpuConf({}))
        # program-affecting conf changes the fingerprint
        fp_batch = aot.conf_fingerprint(TpuConf(
            {"spark.rapids.tpu.sql.batchSizeRows": 12345}))
        assert fp_batch != fp_default
        # obs/service/aot-bookkeeping groups are excluded
        fp_obs = aot.conf_fingerprint(TpuConf(
            {"spark.rapids.tpu.obs.stats.enabled": False}))
        fp_dir = aot.conf_fingerprint(TpuConf(
            {"spark.rapids.tpu.compile.aot.cacheDir": "/elsewhere"}))
        assert fp_obs == fp_default
        assert fp_dir == fp_default


# ---------------------------------------------------------------------------
# auditor coverage over the bucketed program registry
# ---------------------------------------------------------------------------

class TestAuditorCoverage:
    def test_required_programs_match_bucketed_registry(self):
        from spark_rapids_tpu.analysis.program_audit import \
            REQUIRED_PROGRAMS
        assert frozenset(REQUIRED_PROGRAMS) == aot.BUCKETED_PROGRAMS

    def test_aot_coverage_gaps_empty_and_planted_gap_trips(self):
        from spark_rapids_tpu.analysis import program_audit as PA
        specs = PA.collect_specs()
        assert PA.aot_coverage_gaps(specs) == []
        planted = [s for s in specs if s.name != "join_probe"]
        assert PA.aot_coverage_gaps(planted) == ["join_probe"]


# ---------------------------------------------------------------------------
# lint scope: the AOT modules carry the plane discipline
# ---------------------------------------------------------------------------

class TestLintScope:
    def test_scopes_cover_aot_and_warmup(self):
        from spark_rapids_tpu.analysis import lint
        for rel in ("spark_rapids_tpu/compile/aot.py",
                    "spark_rapids_tpu/service/warmup.py"):
            scopes = lint._scopes_for(rel)
            assert {lint.SYNC001, lint.OBS002, lint.HYG002} <= scopes, rel

    def test_seeded_fixture_trips_all_three_rules(self):
        from spark_rapids_tpu.analysis import lint
        path = os.path.join(REPO_ROOT, "tests", "lint_fixtures",
                            "aot_sync.py")
        with open(path, "r", encoding="utf-8") as f:
            findings = lint.lint_source(f.read(), path)
        rules = [f.rule for f in findings]
        assert rules.count(lint.SYNC001) >= 3
        assert lint.OBS002 in rules
        assert lint.HYG002 in rules

    def test_shipped_modules_lint_clean(self):
        from spark_rapids_tpu.analysis import lint
        for rel in ("spark_rapids_tpu/compile/aot.py",
                    "spark_rapids_tpu/service/warmup.py"):
            path = os.path.join(REPO_ROOT, rel)
            with open(path, "r", encoding="utf-8") as f:
                findings = lint.lint_source(
                    f.read(), rel, scopes=lint._scopes_for(rel))
            assert findings == [], rel


# ---------------------------------------------------------------------------
# mask-correctness: bucketed == unbucketed, bit for bit
# ---------------------------------------------------------------------------

def _result_sha(conf_extra):
    from harness import with_tpu_session

    def fn(s):
        df = (s.create_dataframe(
                {"k": [i % 13 for i in range(5000)],
                 "v": [i * 3 + 1 for i in range(5000)]},
                num_partitions=3)
              .filter(F.col("v") % 5 != 0)
              .group_by("k").agg(F.sum("v").alias("sv"),
                                 F.count("v").alias("cv")))
        rows = sorted(df.collect())
        return hashlib.sha256(repr(rows).encode()).hexdigest()

    settings = {"spark.rapids.tpu.sql.batchSizeRows": 700}
    settings.update(conf_extra)
    return with_tpu_session(fn, settings)


class TestBucketedShaIdentical:
    @pytest.mark.parametrize("parallelism", [1, 4])
    @pytest.mark.parametrize("superstage", [True, False])
    def test_ratio4_matches_unbucketed(self, parallelism, superstage):
        base = {
            "spark.rapids.tpu.exec.pipelineParallelism": parallelism,
            "spark.rapids.tpu.sql.superstage": superstage,
        }
        unbucketed = _result_sha(
            {**base, "spark.rapids.tpu.compile.aot.enabled": False})
        aot.reset()
        bucketed = _result_sha(
            {**base, "spark.rapids.tpu.compile.aot.bucketRatio": 4})
        assert bucketed == unbucketed


# ---------------------------------------------------------------------------
# cross-process persistent reuse (subprocess against a seeded dir)
# ---------------------------------------------------------------------------

_CHILD_SRC = r"""
import json, os, sys
sys.path.insert(0, os.path.join(sys.argv[1], "benchmarks"))
import tpcds
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import compile_watch
from spark_rapids_tpu.columnar import pending

cache_dir, data_dir = sys.argv[2], sys.argv[3]
s = TpuSession(TpuConf({
    "spark.rapids.tpu.sql.enabled": True,
    "spark.rapids.tpu.compile.aot.cacheDir": cache_dir,
}))
tpcds.register(s, data_dir)
rows = sorted(s.sql(tpcds.QUERIES["q3"]).collect())
import hashlib
sha = hashlib.sha256(repr(rows).encode()).hexdigest()
recs = compile_watch.records_since(0)
print(json.dumps({
    "sha": sha,
    "compiles": sum(1 for r in recs if r.get("origin") != "persistent"),
    "persistent_hits": compile_watch.persistent_hits(),
    "flushes": pending.FLUSH_COUNT,
}))
"""


@pytest.mark.slow
class TestPersistentCacheAcrossProcesses:
    def test_child_against_seeded_dir_compiles_nothing(self, tmp_path):
        """Child A seeds the cache dir cold; child B re-runs q3 in a
        fresh process and must satisfy every first-call from the
        persistent cache: zero new compile records (the
        tpu_compile_seconds count stays untouched), >0 persistent
        hits, sha-identical results."""
        data_dir = str(tmp_path / "sf")
        sys.path.insert(0, os.path.join(REPO_ROOT, "benchmarks"))
        import tpcds
        tpcds.generate(data_dir, scale=0.002, seed=11)
        cache_dir = str(tmp_path / "aot_cache")
        env = dict(os.environ, JAX_PLATFORMS="cpu")

        def run_child():
            out = subprocess.run(
                [sys.executable, "-c", _CHILD_SRC, REPO_ROOT,
                 cache_dir, data_dir],
                capture_output=True, text=True, env=env, timeout=300,
                cwd=REPO_ROOT)
            assert out.returncode == 0, out.stderr[-2000:]
            return json.loads(out.stdout.strip().splitlines()[-1])

        cold = run_child()
        assert cold["compiles"] > 0          # child A really compiled
        assert os.path.exists(os.path.join(cache_dir,
                                           "aot_manifest.json"))
        warm = run_child()
        assert warm["sha"] == cold["sha"]
        assert warm["compiles"] == 0, warm
        assert warm["persistent_hits"] > 0
