"""UDF compiler + python UDF tests.

Reference pattern: udf-compiler OpcodeSuite + udf_test.py.
"""
import pytest
import math

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.expr import core as ec
from spark_rapids_tpu.udf import udf, pandas_udf, compile_udf
from spark_rapids_tpu.udf.python_udf import PythonUDF

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import IntGen, FloatGen, StringGen, gen_df

N = 100


class TestCompiler:
    def _compiled(self, fn, nargs=1):
        args = [ec.AttributeReference(f"a{i}", T.INT64)
                for i in range(nargs)]
        return compile_udf(fn, args)

    def test_compiles_arithmetic(self):
        e = self._compiled(lambda x: x * 2 + 1)
        assert e is not None
        assert "2" in repr(e)

    def test_compiles_comparison_ternary(self):
        e = self._compiled(lambda x: 1 if x > 0 else -1)
        assert e is not None

    def test_compiles_two_args(self):
        e = self._compiled(lambda x, y: (x + y) * (x - y), nargs=2)
        assert e is not None

    def test_compiles_math(self):
        e = self._compiled(lambda x: math.sqrt(abs(x)))
        assert e is not None

    def test_literal_range_loop_now_compiles(self):
        # round 3: literal-range loops unroll (CFG.scala loop role) —
        # this shape used to be a fallback
        def f(x):
            total = 0
            for i in range(3):
                total += x
            return total
        assert self._compiled(f) is not None

    def test_fallback_on_closure(self):
        y = 5
        assert self._compiled(lambda x: x + y) is None


class TestUdfEndToEnd:
    def test_compiled_udf_matches(self):
        my = udf(lambda x: x * 3 + 2, return_type=T.INT64)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": IntGen(lo=-100, hi=100)}, N)
            .select(my(F.col("a")).alias("r")))

    def test_conditional_udf(self):
        my = udf(lambda x: x if x > 0 else -x, return_type=T.INT64)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": IntGen(lo=-100, hi=100)}, N)
            .select(my(F.col("a")).alias("r")))

    def test_rowwise_fallback_udf(self):
        # closure forces the row-wise path
        k = 7
        my = udf(lambda x: None if x is None else x % k,
                 return_type=T.INT64)
        # verify the fallback engaged
        e = my(F.col("a")).expr
        assert isinstance(e, PythonUDF)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": IntGen(lo=0, hi=1000)}, N)
            .select(my(F.col("a")).alias("r")))

    def test_pandas_udf(self):
        my = pandas_udf(lambda s: s * 2.5, return_type=T.FLOAT64)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": FloatGen(no_nans=True)}, N)
            .select(my(F.col("a")).alias("r")))

    def test_udf_in_filter(self):
        my = udf(lambda x: x > 10, return_type=T.BOOL)
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, {"a": IntGen(lo=0, hi=30)}, N)
            .filter(my(F.col("a"))))


class TestNativeTpuUDF:
    """TpuUDF: the RapidsUDF.java-role interface — user columnar code
    running natively on device."""

    def test_array_math_udf_parity(self):
        from spark_rapids_tpu.udf import tpu_udf
        from spark_rapids_tpu.columnar import dtypes as T
        from harness import assert_tpu_and_cpu_are_equal_collect
        from spark_rapids_tpu.api import functions as F

        @tpu_udf(return_type=T.FLOAT64)
        def scaled(x, y):
            return x * 2.0 + y

        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.range(0, 50).select(
                F.col("id"),
                scaled(F.col("id").cast("double"),
                       (F.col("id") % 3).cast("double")).alias("u")))

    def test_null_semantics(self):
        import pyarrow as pa
        from spark_rapids_tpu.udf import tpu_udf
        from spark_rapids_tpu.columnar import dtypes as T
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F

        @tpu_udf(return_type=T.INT64)
        def inc(x):
            return x + 1

        rows = with_tpu_session(
            lambda s: s.create_dataframe(pa.table({"a": [1, None, 3]}))
            .select(inc(F.col("a")).alias("u")).collect())
        assert rows == [(2,), (None,), (4,)]

    def test_custom_udf_class_on_strings(self):
        import jax.numpy as jnp
        from spark_rapids_tpu.udf import TpuUDF, tpu_udf
        from spark_rapids_tpu.columnar import dtypes as T
        from spark_rapids_tpu.columnar.column import Column, StringColumn
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F
        import pyarrow as pa

        class ByteLen(TpuUDF):
            """Byte length via the offsets buffer — device int math (the
            StringWordCount udf-examples pattern)."""
            return_type = T.INT32

            def evaluate_columnar(self, num_rows, col: StringColumn):
                lens = (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)
                return Column(T.INT32, lens, col.validity)

        fn = tpu_udf(ByteLen())
        rows = with_tpu_session(
            lambda s: s.create_dataframe(
                pa.table({"s": ["ab", None, "xyzé"]}))
            .select(fn(F.col("s")).alias("n")).collect())
        assert rows == [(2,), (None,), (5,)]

    def test_runs_on_tpu_plan(self):
        from spark_rapids_tpu.udf import tpu_udf
        from spark_rapids_tpu.columnar import dtypes as T
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F

        @tpu_udf(return_type=T.INT64)
        def tri(x):
            return x * (x + 1) // 2

        rows = with_tpu_session(
            lambda s: s.range(0, 10).select(tri(F.col("id")).alias("t"))
            .collect(),
            conf={"spark.rapids.tpu.sql.test.enabled": "true"})
        assert rows[-1] == (45,)

    def test_host_state_not_baked_into_trace(self):
        """A UDF with mutable host state must NOT fuse into a jit trace
        (it would run once at trace time and return stale constants)."""
        from spark_rapids_tpu.udf import tpu_udf
        from spark_rapids_tpu.columnar import dtypes as T
        from harness import with_tpu_session
        from spark_rapids_tpu.api import functions as F
        calls = {"n": 0}

        @tpu_udf(return_type=T.INT64)
        def stateful(x):
            calls["n"] += 1
            return x + calls["n"]

        def fn(s):
            df = s.range(0, 8, num_partitions=2).select(
                stateful(F.col("id")).alias("u"))
            return df.collect()
        rows = with_tpu_session(fn)
        # two partitions -> two eager invocations with distinct state;
        # under (wrong) fusion both batches would see the same constant
        assert calls["n"] >= 2


class TestUdfLoopCompilation:
    """Bounded loop unrolling (the CFG.scala:44 loop-compilation role:
    literal-range for-loops become straight-line expressions)."""

    def _batch(self):
        import numpy as np
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        return ColumnarBatch.from_pydict(
            {"x": np.array([2.0, 0.5, 3.0, -1.0])})

    def _check(self, fn):
        import numpy as np
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec
        e = compile_udf(fn, [ec.AttributeReference("x")])
        assert e is not None, "expected the loop to compile"
        b = self._batch()
        got = np.asarray(e.bind(b.schema).columnar_eval(b).data)[:4]
        want = [fn(v) for v in [2.0, 0.5, 3.0, -1.0]]
        assert np.allclose(got, want), (got, want)

    def test_range_loop_unrolls(self):
        def poly(x):
            acc = 0.0
            for i in range(4):
                acc = acc + x ** i
            return acc
        self._check(poly)

    def test_branch_inside_loop(self):
        def f(x):
            acc = 0.0
            for i in range(3):
                if x > i:
                    acc = acc + i
                else:
                    acc = acc - 1.0
            return acc
        self._check(f)

    def test_range_start_stop_step(self):
        def f(x):
            acc = x
            for i in range(2, 10, 3):
                acc = acc * 1.0 + i
            return acc
        self._check(f)

    def test_unroll_cap_falls_back(self):
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec

        def f(x):
            acc = 0.0
            for i in range(1000):
                acc = acc + i
            return acc
        assert compile_udf(f, [ec.AttributeReference("x")]) is None

    def test_data_dependent_loop_falls_back(self):
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec

        def f(x):
            acc = 0.0
            for i in range(int(x)):
                acc = acc + i
            return acc
        assert compile_udf(f, [ec.AttributeReference("x")]) is None


class TestUdfExamples:
    """The udf-examples/ role: each reference example flavor has a
    working TPU-framework analogue (spark_rapids_tpu/udf/examples.py)."""

    def test_url_roundtrip_and_word_count(self):
        from harness import assert_tpu_and_cpu_are_equal_collect
        from spark_rapids_tpu.udf import examples as ex

        def q(s):
            df = s.create_dataframe({
                "s": ["a b&c", "hello world x", None, "q=1&r=2 s"]})
            enc = df.with_column("e", ex.url_encode(F.col("s")))
            dec = enc.with_column("d", ex.url_decode(F.col("e")))
            return dec.with_column("w", ex.word_count(F.col("s")))
        rows = assert_tpu_and_cpu_are_equal_collect(q)
        for s, e, d, w in rows:
            assert d == s
            assert w == (len(s.split()) if s is not None else None)

    def test_polynomial_compiles_to_expressions(self):
        from spark_rapids_tpu.udf import examples as ex
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec
        # the example must be translatable (no python per row)
        assert compile_udf(lambda x: 3.0 * x * x + 2.0 * x + 1.0,
                           [ec.AttributeReference("x")]) is not None
        from harness import assert_tpu_and_cpu_are_equal_collect

        def q(s):
            import numpy as np
            df = s.create_dataframe({"x": np.array([0.0, 1.0, -2.0])})
            return df.select(ex.polynomial(F.col("x")).alias("p"))
        rows = sorted(assert_tpu_and_cpu_are_equal_collect(q))
        assert [r[0] for r in rows] == [1.0, 6.0, 9.0]

    def test_cosine_similarity_device_udf(self):
        from harness import with_tpu_session
        from spark_rapids_tpu.udf import examples as ex
        import math

        def q(s):
            df = s.create_dataframe(
                [([1.0, 0.0], [1.0, 0.0]),
                 ([1.0, 0.0], [0.0, 1.0]),
                 ([1.0, 2.0], [2.0, 4.0]),
                 ([1.0, 2.0], [1.0, 2.0, 3.0])],
                schema=_arr_schema())
            return df.select(
                ex.cosine_similarity(F.col("a"), F.col("b")).alias("c"))
        rows = with_tpu_session(lambda s: q(s).collect())
        vals = [r[0] for r in rows]
        assert abs(vals[0] - 1.0) < 1e-9
        assert abs(vals[1]) < 1e-9
        assert abs(vals[2] - 1.0) < 1e-9
        assert vals[3] is None          # length mismatch -> null


def _arr_schema():
    from spark_rapids_tpu.columnar.schema import Field, Schema
    from spark_rapids_tpu.columnar import dtypes as T
    at = T.ArrayType(T.FLOAT64)
    return Schema([Field("a", at), Field("b", at)])


class TestCompilerBreadth:
    """Round-4 opcode breadth (Instruction.scala:198 role): boolean
    short-circuit, chained comparisons, membership, is None, bitwise
    invert — all must COMPILE (not fall back) and match the row-wise
    Python evaluation."""

    CASES = [
        ("and_or", lambda x, y: (x > 0 and y < 5) or x == -3),
        ("chained", lambda x, y: 0 < x < 10),
        ("membership", lambda x, y: x in (1, 2, 3, 7)),
        ("not_in", lambda x, y: y not in (0, 4)),
        ("is_none_ternary", lambda x, y: 0 if x is None else x + y),
        ("invert", lambda x, y: ~x + y),
        ("truthy_int", lambda x, y: 1 if x and y else 0),
    ]

    @pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
    def test_compiles_and_matches(self, name, fn):
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.columnar import dtypes as T
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar import Schema, Field
        args = [ec.AttributeReference("x", T.INT64, True),
                ec.AttributeReference("y", T.INT64, True)]
        expr = compile_udf(fn, args)
        assert expr is not None, f"{name} must compile"
        xs = [1, 2, -3, 0, 7, 9, 11, 4]
        ys = [4, 0, 1, 5, 7, -2, 3, 4]
        batch = ColumnarBatch.from_pydict(
            {"x": xs, "y": ys},
            schema=Schema([Field("x", T.INT64), Field("y", T.INT64)]))
        bound = expr.bind(batch.schema)
        got = ec.eval_as_column(bound, batch).to_pylist(len(xs))
        want = [fn(x, y) for x, y in zip(xs, ys)]
        norm = lambda v: (None if v is None else
                          bool(v) if isinstance(v, bool) else int(v))
        assert [norm(g) for g in got] == [norm(w) for w in want], name

    def test_is_none_with_actual_nulls(self):
        """The is-None branch with REAL None inputs: compiled result
        must match row-wise Python, including null rows."""
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.columnar import dtypes as T
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar import Schema, Field
        fn = lambda x, y: 0 if x is None else x + y
        args = [ec.AttributeReference("x", T.INT64, True),
                ec.AttributeReference("y", T.INT64, True)]
        expr = compile_udf(fn, args)
        assert expr is not None
        xs = [1, None, -3, None, 7]
        ys = [4, 0, 1, 5, 7]
        batch = ColumnarBatch.from_pydict(
            {"x": xs, "y": ys},
            schema=Schema([Field("x", T.INT64), Field("y", T.INT64)]))
        got = ec.eval_as_column(expr.bind(batch.schema),
                                batch).to_pylist(len(xs))
        want = [fn(x, y) for x, y in zip(xs, ys)]
        assert [int(g) for g in got] == want

    def test_membership_null_matches_python(self):
        """None in (1,2,3) is False in Python; the compiled form must
        agree (not SQL NULL) — the silent-divergence hazard of
        replacing a Python fallback with SQL expressions."""
        from spark_rapids_tpu.udf.compiler import compile_udf
        from spark_rapids_tpu.expr import core as ec
        from spark_rapids_tpu.columnar import dtypes as T
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar import Schema, Field
        for fn in (lambda x: x in (1, 2, 3),
                   lambda x: x not in (1, 2, 3)):
            expr = compile_udf(
                fn, [ec.AttributeReference("x", T.INT64, True)])
            assert expr is not None
            xs = [1, None, 5]
            batch = ColumnarBatch.from_pydict(
                {"x": xs}, schema=Schema([Field("x", T.INT64)]))
            col = ec.eval_as_column(expr.bind(batch.schema), batch)
            got = col.to_pylist(3)
            want = [fn(x) for x in xs]
            assert [bool(g) for g in got] == want
            assert all(v is not None for v in got)


class TestCompilerMatrix:
    """Wide compile-vs-fallback matrix (udf-compiler test coverage
    role): every compilable shape's device expression must match the
    pure-Python row result EXACTLY (the compiled expression replaces a
    row-wise fallback); refused shapes must return None (silent
    fallback contract)."""

    def _eval_compiled(self, fn, values, dtype=T.INT64):
        import numpy as np
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.columnar.schema import Field, Schema
        from spark_rapids_tpu.columnar import Column
        e = compile_udf(fn, [ec.BoundReference(0, dtype, "a0")])
        if e is None:
            return None
        if dtype == T.STRING:
            col = Column.from_numpy(list(values), dtype=T.STRING)
        else:
            col = Column.from_numpy(
                np.asarray(values, dtype.np_dtype), dtype=dtype)
        schema = Schema([Field("a0", dtype, True)])
        batch = ColumnarBatch(schema, [col], len(values))
        out = ec.eval_as_column(e, batch)
        vals, valid = out.to_numpy(len(values)) if not hasattr(
            out, "to_pylist") or out.dtype != T.STRING else (None, None)
        if out.dtype == T.STRING:
            return out.to_pylist(len(values))
        return [v if ok else None for v, ok in zip(vals, valid)]

    def _check(self, fn, values, dtype=T.INT64, approx=False):
        got = self._eval_compiled(fn, values, dtype)
        assert got is not None, "expected shape to compile"
        expect = [fn(v) for v in values]
        for g, w in zip(got, expect):
            if approx and isinstance(w, float):
                assert abs(g - w) <= 1e-9 * max(abs(w), 1.0), (g, w)
            elif isinstance(w, bool):
                assert bool(g) == w, (g, w)
            elif isinstance(w, float):
                assert g == w or abs(g - w) < 1e-12, (g, w)
            else:
                assert g == w, (g, w)

    def _refused(self, fn, nargs=1):
        args = [ec.BoundReference(i, T.INT64, f"a{i}")
                for i in range(nargs)]
        assert compile_udf(fn, args) is None

    I = list(range(-20, 21, 3)) + [0, 1, -1, 17]
    F = [(-2.5 + 0.37 * k) for k in range(12)]
    S = ["Hello", "world", "  pad  ", "", "Ab", "prefix_x"]

    # -- arithmetic / comparison shapes ---------------------------------
    def test_m01_linear(self):
        self._check(lambda x: x * 3 - 7, self.I)

    def test_m02_nested_arith(self):
        self._check(lambda x: (x + 1) * (x - 1) + x, self.I)

    def test_m03_pymod_negative_dividend(self):
        # python % follows the divisor sign — compiled as Pmod
        self._check(lambda x: x % 5, self.I)

    def test_m04_pymod_negative_divisor_refused(self):
        # python's % with a negative divisor differs from Pmod: fallback
        self._refused(lambda x: x % -3)

    def test_m05_floordiv_refused(self):
        # // floor-divides in python but truncates in SQL: fallback
        self._refused(lambda x: x // 3)

    def test_m06_power(self):
        self._check(lambda x: x ** 2, self.I)

    def test_m07_bitops(self):
        self._check(lambda x: (x & 12) | (x ^ 5), self.I)

    def test_m08_shifts(self):
        self._check(lambda x: (x << 2) >> 1, [v for v in self.I
                                              if v >= 0])

    def test_m09_ternary(self):
        self._check(lambda x: x if x > 0 else -x, self.I)

    def test_m10_chained_compare(self):
        self._check(lambda x: 1 if 0 < x < 10 else 0, self.I)

    def test_m11_bool_ops(self):
        self._check(lambda x: (x > 2) and (x < 15), self.I)

    def test_m12_not(self):
        self._check(lambda x: not (x > 0), self.I)

    def test_m13_membership(self):
        self._check(lambda x: x in (1, 4, 17), self.I)

    def test_m14_min_max_abs(self):
        self._check(lambda x: max(min(abs(x), 10), 2), self.I)

    # -- math intrinsics -------------------------------------------------
    def test_m15_sqrt_abs(self):
        self._check(lambda x: math.sqrt(abs(x)), self.F, T.FLOAT64,
                    approx=True)

    def test_m16_exp_log(self):
        self._check(lambda x: math.log(math.exp(x) + 1.0), self.F,
                    T.FLOAT64, approx=True)

    def test_m17_trig(self):
        self._check(lambda x: math.sin(x) * math.cos(x) + math.tan(x),
                    self.F, T.FLOAT64, approx=True)

    def test_m18_floor_ceil(self):
        self._check(lambda x: math.floor(x) + math.ceil(x), self.F,
                    T.FLOAT64)

    def test_m19_atan2(self):
        self._check(lambda x: math.atan2(x, 2.0), self.F, T.FLOAT64,
                    approx=True)

    def test_m20_pow2(self):
        self._check(lambda x: math.pow(abs(x) + 0.5, 1.5), self.F,
                    T.FLOAT64, approx=True)

    def test_m21_pi_const(self):
        self._check(lambda x: x * math.pi + math.e, self.F, T.FLOAT64,
                    approx=True)

    def test_m22_fabs(self):
        self._check(lambda x: math.fabs(x), self.F, T.FLOAT64)

    # -- casts ----------------------------------------------------------
    def test_m23_int_cast(self):
        self._check(lambda x: int(x), self.F, T.FLOAT64)

    def test_m24_float_cast(self):
        self._check(lambda x: float(x) / 2.0, self.I)

    # -- string methods --------------------------------------------------
    def test_m25_upper(self):
        self._check(lambda s: s.upper(), self.S, T.STRING)

    def test_m26_lower_strip(self):
        self._check(lambda s: s.strip().lower(), self.S, T.STRING)

    def test_m27_len(self):
        self._check(lambda s: len(s), self.S, T.STRING)

    def test_m28_startswith(self):
        self._check(lambda s: s.startswith("pre"), self.S, T.STRING)

    def test_m29_endswith(self):
        self._check(lambda s: s.endswith("x"), self.S, T.STRING)

    def test_m30_replace(self):
        self._check(lambda s: s.replace("l", "L"), self.S, T.STRING)

    def test_m31_concat(self):
        self._check(lambda s: s + "_suffix", self.S, T.STRING)

    def test_m32_replace_nonliteral_arg_refused(self):
        # device string predicates take LITERAL patterns only
        self._refused(lambda s: s.replace(s, "X"))

    # -- loops -----------------------------------------------------------
    def test_m33_for_range(self):
        def f(x):
            acc = 0
            for i in range(4):
                acc = acc + x * i
            return acc
        self._check(f, self.I)

    def test_m34_while_literal_counter(self):
        def f(x):
            acc = x
            i = 0
            while i < 5:
                acc = acc + i
                i = i + 1
            return acc
        self._check(f, self.I)

    def test_m35_while_data_dependent_refused(self):
        def f(x):
            while x > 0:
                x = x - 1
            return x
        self._refused(f)

    def test_m36_nested_loop(self):
        def f(x):
            acc = 0
            for i in range(3):
                for j in range(2):
                    acc = acc + x + i * j
            return acc
        self._check(f, self.I)

    def test_m37_branch_in_while(self):
        def f(x):
            acc = 0
            i = 0
            while i < 4:
                acc = acc + (x if x > i else i)
                i = i + 1
            return acc
        self._check(f, self.I)
