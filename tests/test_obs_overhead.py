"""Observability tax diet tests (obs/overhead.py + the r17 hot-path
diet across the planes).

Five surfaces:

1. The self-meter — clock/note bill nanoseconds to interned plane
   counters with zero allocation; disabled mode costs one global read
   and records nothing; snapshot/delta_ms follow the FLUSH_COUNT
   counter-delta discipline; plane shares sum exactly to the total.
2. Query integration — a collected query's event record carries an
   ``obs_self`` block whose plane keys are the meter's PLANES and
   whose total is the sum of the shares; the Prometheus exposition
   exports ``tpu_obs_self_seconds_total{plane=...}`` via collect-time
   callbacks; a session with the meter off records neither.
3. The planes-on/planes-off contract — the SAME query with every obs
   conf disabled returns a sha-identical arrow table and the exact
   same warm FLUSH_COUNT delta (observability adds zero device round
   trips and never touches results).
4. Sketch sampling (obs.stats.sampleEvery) — the want_sketch gate
   draws every Nth ticket; a sampled exchange entry keeps rows/bytes/
   skew exact, drops per-row null counts (cannot extrapolate
   honestly), and labels itself with a ``sample`` block; exact mode
   (the test-harness default via SPARK_RAPIDS_TPU_OBS_STATS_EXACT)
   has no label and exact nulls.
5. The history-writer diet — rows are serialized ONCE caller-side
   into opaque bytes, the writer drains bursts into batches with one
   open per batch (the r16 write-p99 regression: dumps+open per row),
   nothing is lost across a contended burst, and the cold routing of
   compile-bearing dispatch windows keeps the warm summary clean.
"""
import hashlib
import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar import pending
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import (compile_watch, history, overhead,
                                  profile, stats)
from spark_rapids_tpu.obs.prom import render_text
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.service.metrics import QueryMetrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every default-on observability conf, off — the bench's
#: all_planes_on_vs_off denominator configuration (bench.py run_engine)
ALL_PLANES_OFF = {
    "spark.rapids.tpu.obs.trace.enabled": False,
    "spark.rapids.tpu.obs.flightRecorder.enabled": False,
    "spark.rapids.tpu.obs.stats.enabled": False,
    "spark.rapids.tpu.obs.timeline.enabled": False,
    "spark.rapids.tpu.obs.compile.enabled": False,
    "spark.rapids.tpu.obs.slo.enabled": False,
    "spark.rapids.tpu.obs.net.enabled": False,
    "spark.rapids.tpu.obs.mem.enabled": False,
    "spark.rapids.tpu.obs.cost.enabled": False,
    "spark.rapids.tpu.obs.doctor.enabled": False,
    "spark.rapids.tpu.obs.history.enabled": False,
    "spark.rapids.tpu.obs.anomaly.enabled": False,
    "spark.rapids.tpu.obs.overhead.enabled": False,
}


@pytest.fixture(autouse=True)
def _meter_reset():
    """The meter is process-global: restore the default-on config and
    zero the counters around every test."""
    overhead.configure(TpuConf({}))
    overhead.reset()
    yield
    overhead.configure(TpuConf({}))
    overhead.reset()


# ---------------------------------------------------------------------------
# 1. self-meter unit
# ---------------------------------------------------------------------------

class TestMeter:
    def test_clock_note_bills_one_plane(self):
        t0 = overhead.clock()
        assert t0 > 0
        overhead.note(overhead.P_STATS, t0)
        sec = overhead.stats_section()
        assert sec["enabled"] is True
        assert sec["planes"]["stats"]["calls"] == 1
        assert sec["planes"]["stats"]["ms"] >= 0.0
        for plane in overhead.PLANES:
            if plane != "stats":
                assert sec["planes"][plane]["calls"] == 0

    def test_note_accepts_caller_stamp(self):
        # timeline/netplane pass an existing perf_counter_ns stamp so
        # the close of their own timing window doubles as the meter
        # start — no extra clock read on those paths
        stamp = time.perf_counter_ns()
        overhead.note(overhead.P_NET, stamp)
        assert overhead.stats_section()["planes"]["net"]["calls"] == 1

    def test_disabled_clock_zero_and_note_skips(self):
        overhead.configure(TpuConf(
            {"spark.rapids.tpu.obs.overhead.enabled": False}))
        assert overhead.is_enabled() is False
        assert overhead.clock() == 0
        overhead.note(overhead.P_STATS, 0)           # the clock() path
        overhead.note(overhead.P_STATS,
                      time.perf_counter_ns())        # a caller stamp
        sec = overhead.stats_section()
        assert sec["enabled"] is False
        assert all(p["calls"] == 0 for p in sec["planes"].values())

    def test_snapshot_delta_ms_counter_discipline(self):
        since = overhead.snapshot()
        assert since == tuple([0] * len(overhead.PLANES))
        t0 = overhead.clock()
        overhead.note(overhead.P_HISTORY, t0)
        d = overhead.delta_ms(since)
        assert set(d) == set(overhead.PLANES)
        assert d["history"] >= 0.0
        assert all(d[p] == 0.0 for p in overhead.PLANES
                   if p != "history")
        # a fresh snapshot zeroes the window
        assert all(v == 0.0 for v in
                   overhead.delta_ms(overhead.snapshot()).values())

    def test_shares_sum_exactly_to_total(self):
        for i, _plane in enumerate(overhead.PLANES):
            t0 = overhead.clock()
            time.sleep(0.001 * (i % 3 + 1))
            overhead.note(i, t0)
        sec = overhead.stats_section()
        total = sum(p["ms"] for p in sec["planes"].values())
        # both sides are the same _NS cells — rounding is the only slack
        assert sec["total_ms"] == pytest.approx(total, abs=0.01)
        assert overhead.total_ms() == pytest.approx(total, abs=0.01)

    def test_reset_zeroes_without_reallocating(self):
        ns_list = overhead._NS
        overhead.note(overhead.P_COST, overhead.clock())
        overhead.reset()
        assert overhead._NS is ns_list           # preallocated, kept
        assert overhead.snapshot() == tuple([0] * len(overhead.PLANES))


# ---------------------------------------------------------------------------
# 2. query integration + export
# ---------------------------------------------------------------------------

def _small_query(sess):
    df = sess.range(0, 512, num_partitions=2) \
        .select((F.col("id") % 7).alias("k"), F.col("id").alias("v")) \
        .group_by("k").agg(F.sum("v").alias("sv"))
    return df


class TestQueryMetered:
    def test_event_record_carries_obs_self(self):
        s = TpuSession(TpuConf({}))
        _small_query(s).collect()
        rec = s.last_query_event
        assert rec is not None and "obs_self" in rec
        obs = rec["obs_self"]
        assert set(obs["planes"]) == set(overhead.PLANES)
        assert obs["total_ms"] == pytest.approx(
            sum(obs["planes"].values()), abs=0.01)
        # default-on planes did real work inside this query's window
        assert obs["total_ms"] >= 0.0
        assert overhead.stats_section()["planes"]["stats"]["calls"] > 0

    def test_prometheus_export_collect_time(self):
        overhead.note(overhead.P_MEM, overhead.clock())
        text = render_text(get_registry())
        assert "tpu_obs_self_seconds_total" in text
        for plane in overhead.PLANES:
            assert f'plane="{plane}"' in text

    def test_meter_off_session_records_nothing(self):
        s = TpuSession(TpuConf(
            {"spark.rapids.tpu.obs.overhead.enabled": False}))
        _small_query(s).collect()
        rec = s.last_query_event
        assert rec is not None and "obs_self" not in rec
        sec = overhead.stats_section()
        assert all(p["calls"] == 0 for p in sec["planes"].values())


# ---------------------------------------------------------------------------
# 3. planes-on vs planes-off: identical results, identical flushes
# ---------------------------------------------------------------------------

def _table_sha(t) -> str:
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    return hashlib.sha256(sink.getvalue().to_pybytes()).hexdigest()


def _run_mode(conf_extra):
    """Warm a deterministic query, then return (sha, warm flush
    delta) — the FLUSH_COUNT process-wide-counter-delta discipline."""
    s = TpuSession(TpuConf(dict(conf_extra)))
    df = s.range(0, 4096, num_partitions=4) \
        .select((F.col("id") % 13).alias("k"), F.col("id").alias("v")) \
        .filter(F.col("v") % 3 != 0) \
        .group_by("k").agg(F.sum("v").alias("sv"),
                           F.count().alias("c")) \
        .sort("k")
    df.to_arrow()                                  # warm
    f0 = pending.FLUSH_COUNT
    out = df.to_arrow()
    return _table_sha(out), pending.FLUSH_COUNT - f0


class TestPlanesOnOff:
    def test_results_sha_identical_and_flush_delta_exact(self):
        sha_on, flushes_on = _run_mode({})
        sha_off, flushes_off = _run_mode(ALL_PLANES_OFF)
        assert sha_on == sha_off
        assert flushes_on == flushes_off


# ---------------------------------------------------------------------------
# 4. sketch sampling
# ---------------------------------------------------------------------------

class _Resolved:
    """Stand-in for a resolved pending-pool staged value."""

    def __init__(self, arr):
        self.np = np.asarray(arr)
        self.resolved = True


def _handles(m=64, nparts=2):
    return stats.ExchangeBatchStats(
        _Resolved(np.ones((nparts, m), np.int8)),
        _Resolved(np.zeros(nparts, np.int64)),
        _Resolved(np.zeros(nparts, np.uint64)),
        _Resolved(np.zeros(nparts, np.uint64)),
        None)


class TestSampling:
    def test_harness_forces_exact_mode(self):
        # tests/conftest.py sets SPARK_RAPIDS_TPU_OBS_STATS_EXACT so
        # stats digests stay deterministic under test
        assert os.environ.get("SPARK_RAPIDS_TPU_OBS_STATS_EXACT")
        assert stats.sample_every(TpuConf({})) == 1

    def test_sample_every_reads_conf_without_env(self, monkeypatch):
        monkeypatch.delenv("SPARK_RAPIDS_TPU_OBS_STATS_EXACT",
                           raising=False)
        assert stats.sample_every(TpuConf({})) == 4   # default
        assert stats.sample_every(TpuConf(
            {"spark.rapids.tpu.obs.stats.sampleEvery": 7})) == 7
        assert stats.sample_every(TpuConf(
            {"spark.rapids.tpu.obs.stats.sampleEvery": 0})) == 1

    def test_want_sketch_first_batch_then_every_nth(self):
        acc = stats.ExchangeAcc(2, 64, 8.0, "shuffle", "hash", every=3)
        assert [acc.want_sketch() for _ in range(7)] == \
            [True, False, False, True, False, False, True]
        exact = stats.ExchangeAcc(2, 64, 8.0, "shuffle", "hash",
                                  every=1)
        assert all(exact.want_sketch() for _ in range(5))

    def test_sampled_entry_labeled_rows_exact_nulls_dropped(self):
        acc = stats.ExchangeAcc(2, 64, 8.0, "shuffle", "hash", every=2)
        offsets = np.array([0, 5, 9], np.int64)
        for i in range(4):
            acc.absorb(offsets, _handles() if i % 2 == 0 else None)
        node = SimpleNamespace(_stats_acc=acc)
        entry = stats.finish_exchange(node, conf=TpuConf({}))
        # rows/bytes/skew from the split offsets: exact regardless
        assert entry["rows"] == 36
        assert entry["partitions"][0]["rows"] == 20
        # per-row null tallies cannot be extrapolated from a sample
        assert entry["null_count"] is None
        assert all(p["nulls"] is None for p in entry["partitions"])
        # sketch-derived fields come from the sampled subset, labeled
        assert entry["distinct_est"] is not None
        assert entry["sample"] == {"every": 2, "sketched": 2,
                                   "batches": 4}

    def test_exact_entry_has_no_sample_label(self):
        acc = stats.ExchangeAcc(2, 64, 8.0, "shuffle", "hash", every=1)
        offsets = np.array([0, 5, 9], np.int64)
        for _ in range(3):
            acc.absorb(offsets, _handles())
        node = SimpleNamespace(_stats_acc=acc)
        entry = stats.finish_exchange(node, conf=TpuConf({}))
        assert "sample" not in entry
        assert entry["null_count"] == 0
        assert entry["partitions"][0]["nulls"] == 0


# ---------------------------------------------------------------------------
# 5. history-writer diet + dispatch cold routing
# ---------------------------------------------------------------------------

def _metrics(i=0, exec_ms=10.0):
    m = QueryMetrics(query_id=f"q{i}", tenant="t", priority=0)
    m.execute_ms = exec_ms
    m.queue_wait_ms = 1.0
    m.outcome = "completed"
    return m


@pytest.fixture
def _history_reset():
    history.stop()
    history.reset()
    yield
    history.stop()
    history.configure(TpuConf({}))
    history.reset()


class TestHistoryWriterDiet:
    def test_rows_serialized_once_caller_side(self, tmp_path,
                                              _history_reset):
        history.configure(TpuConf(
            {"spark.rapids.tpu.obs.history.dir": str(tmp_path)}))
        history.stop()                 # keep rows queued, writer gone
        import queue as _pyqueue
        q = _pyqueue.Queue(16)
        history._Q = q
        row = history.record(_metrics(0))
        data, ts = q.get_nowait()
        # the writer handles opaque bytes: dumps ran HERE, not in its
        # timed window (the r16 p99 regression)
        assert isinstance(data, bytes) and data.endswith(b"\n")
        assert json.loads(data) == json.loads(
            json.dumps(row, sort_keys=True))
        assert ts == row["ts"]
        history._Q = None

    def test_contended_burst_batches_without_loss(self, tmp_path,
                                                  _history_reset,
                                                  monkeypatch):
        batches = []
        orig = history._append_batch

        def slow_append(d, batch):
            batches.append(len(batch))
            time.sleep(0.002)          # force queue buildup per drain
            orig(d, batch)

        monkeypatch.setattr(history, "_append_batch", slow_append)
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path),
            "spark.rapids.tpu.obs.history.queueDepth": 4096,
        }))
        n_threads, per_thread = 4, 50

        def flood(tid):
            for i in range(per_thread):
                history.record(_metrics(tid * per_thread + i))

        threads = [threading.Thread(target=flood, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        history.stop()                 # sentinel drains pending batch
        total = n_threads * per_thread
        assert sum(batches) == total, (sum(batches), total)
        # batching actually happened: far fewer opens than rows
        assert len(batches) < total / 2, len(batches)
        # every row landed on disk exactly once, parseable
        rows = []
        for seg in sorted(tmp_path.glob("history-*.jsonl")):
            with open(seg, "r", encoding="utf-8") as f:
                rows += [json.loads(ln) for ln in f if ln.strip()]
        assert len(rows) == total
        assert history.stats_section()["dropped"] == 0

    def test_write_p99_regression_pin(self, tmp_path, _history_reset):
        """Amortized per-row append cost under a contended burst stays
        ORDERS below the r16 regression reading (3920us at bench
        scale); the strict pin is PERF_BASELINE.json's
        history_write_p99_us band — this is the unit-level guard."""
        history.configure(TpuConf({
            "spark.rapids.tpu.obs.history.dir": str(tmp_path),
            "spark.rapids.tpu.obs.history.queueDepth": 4096,
        }))
        for i in range(300):
            history.record(_metrics(i))
        history.stop()
        p99 = history.write_p99_us()
        assert 0 < p99 < 2000.0, p99


class TestDispatchColdRouting:
    def test_compile_bearing_window_routes_to_cold_twin(self):
        marker = profile.begin_query()
        with profile.dispatch(profile.SITE_SPLIT):
            compile_watch.note_compile("test_cold_route", 1_000_000)
        summary = profile.dispatch_summary(marker)
        assert summary["split_cold"]["count"] == 1
        assert "split" not in summary
        # warm roll-up excludes the compile-bearing window entirely
        assert "all" not in summary
        assert summary["cold"]["count"] == 1

    def test_warm_window_stays_warm_and_all_excludes_cold(self):
        marker = profile.begin_query()
        with profile.dispatch(profile.SITE_SPLIT):
            compile_watch.note_compile("test_cold_route2", 1_000_000)
        with profile.dispatch(profile.SITE_SPLIT):
            pass                       # no compile in this window
        summary = profile.dispatch_summary(marker)
        assert summary["split"]["count"] == 1
        assert summary["split_cold"]["count"] == 1
        assert summary["all"]["count"] == 1
        assert summary["cold"]["count"] == 1

    def test_dispatch_cm_pooled_per_thread_site(self):
        cm1 = profile.dispatch(profile.SITE_SPLIT)
        cm2 = profile.dispatch(profile.SITE_SPLIT)
        assert cm1 is cm2
        assert profile.dispatch(profile.SITE_CHAIN_STEP) is not cm1


# ---------------------------------------------------------------------------
# lint rule OBS003 + report surface
# ---------------------------------------------------------------------------

class TestObs003AndSurfaces:
    def test_obs003_flags_allocation_in_record_path(self):
        from spark_rapids_tpu.analysis import lint as AL
        src = ("def note(plane, t0):\n"
               "    cell = {'plane': plane}\n"
               "    return cell\n")
        findings = AL.lint_source(src, "obs/overhead.py")
        assert any(f.rule == AL.OBS003 for f in findings), findings

    def test_obs003_clean_on_preallocated_shape(self):
        from spark_rapids_tpu.analysis import lint as AL
        src = ("_NS = [0] * 4\n\n"
               "def note(plane, t0):\n"
               "    _NS[plane] += t0\n")
        assert [f for f in AL.lint_source(src, "obs/overhead.py")
                if f.rule == AL.OBS003] == []

    def test_shipped_meter_lints_clean(self):
        from spark_rapids_tpu.analysis import lint as AL
        path = os.path.join(REPO_ROOT, "spark_rapids_tpu", "obs",
                            "overhead.py")
        findings = AL.lint_paths([path], scoped=True)
        assert findings == [], AL.format_findings(findings)

    def test_report_renders_obs_self_line_and_tolerates_old_logs(self):
        from spark_rapids_tpu.tools.report import obs_lines
        rec = {"obs_self": {"total_ms": 1.5,
                            "planes": {"stats": 1.0, "net": 0.5}}}
        lines = obs_lines(rec)
        assert any("obs_self_ms=1.5" in ln for ln in lines)
        assert obs_lines({}) == []     # pre-r17 record: no key, no line
