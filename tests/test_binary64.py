"""Bit-exactness tests for the integer softfloat kernels (kernels/binary64.py)

against numpy's IEEE-754 float64, including subnormals, signed zeros,
infinities, NaNs and round-to-nearest-even ties.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from spark_rapids_tpu.kernels import binary64 as b64


def _adversarial_pool(rng, n):
    pools = [
        rng.standard_normal(n),
        rng.standard_normal(n) * 1e300,
        rng.standard_normal(n) * 1e-300,
        np.ldexp(rng.random(n), rng.integers(-1080, 1025, n)),
        rng.integers(-1000, 1000, n).astype(np.float64),
        rng.integers(1, 1000, n).astype(np.float64) * 5e-324,  # subnormals
        np.ldexp(1.0, rng.integers(-1074, 1024, n)),            # powers of 2
    ]
    specials = np.array([
        0.0, -0.0, np.inf, -np.inf, np.nan, 1.0, -1.0, 0.5, 1.5, 2.5, -2.5,
        2.0 ** -1022, 2.0 ** -1074, np.nextafter(2.0 ** -1022, 0), 5e-324,
        1.7976931348623157e308, -1.7976931348623157e308,
        np.nextafter(1.0, 2.0), np.nextafter(1.0, 0.0), 2.0 ** 52,
        2.0 ** 52 + 1, 2.0 ** 53, 2.0 ** 53 + 2, -2.0 ** 63, 2.0 ** 63,
    ])
    vals = np.concatenate(pools + [specials])
    return rng.permutation(vals)


def _bits(x):
    return jnp.asarray(np.asarray(x, np.float64).view(np.int64))


def _floats(bits):
    return np.asarray(bits).view(np.float64)


def _assert_bits_equal(got_f, expect_f, what, atol_ulp=0):
    gb = got_f.view(np.int64)
    eb = expect_f.view(np.int64)
    both_nan = np.isnan(got_f) & np.isnan(expect_f)
    same = (gb == eb) | both_nan
    # -0.0 vs 0.0 for exact zero results: accept either sign only if asked
    if not same.all():
        i = np.nonzero(~same)[0][:10]
        msg = "\n".join(
            f"  in -> got {got_f[j]!r} ({hex(int(gb[j]))}) want "
            f"{expect_f[j]!r} ({hex(int(eb[j]))})" for j in i)
        raise AssertionError(f"{what}: {len(i)}+ mismatches\n{msg}")


@pytest.fixture(scope="module")
def pool():
    rng = np.random.default_rng(42)
    return _adversarial_pool(rng, 2000)


def test_add(pool):
    a = pool
    b = np.roll(pool, 1)
    with np.errstate(all="ignore"):
        expect = a + b
    got = _floats(b64.add(_bits(a), _bits(b)))
    _assert_bits_equal(got, expect, "add")


def test_add_cancellation():
    a = np.array([1.0, 1e300, 3.5, 2.0 ** -1074, 1.0 + 2.0 ** -52])
    b = -a
    got = _floats(b64.add(_bits(a), _bits(b)))
    expect = a + b
    _assert_bits_equal(got, expect, "add-cancel")


def test_sub(pool):
    a = pool
    b = np.roll(pool, 3)
    with np.errstate(all="ignore"):
        expect = a - b
    got = _floats(b64.sub(_bits(a), _bits(b)))
    _assert_bits_equal(got, expect, "sub")


def test_mul(pool):
    a = pool
    b = np.roll(pool, 7)
    with np.errstate(all="ignore"):
        expect = a * b
    got = _floats(b64.mul(_bits(a), _bits(b)))
    _assert_bits_equal(got, expect, "mul")


def test_div(pool):
    a = pool
    b = np.roll(pool, 11)
    with np.errstate(all="ignore"):
        expect = a / b
    got = _floats(b64.div(_bits(a), _bits(b)))
    _assert_bits_equal(got, expect, "div")


def test_sqrt(pool):
    a = np.abs(pool)
    with np.errstate(all="ignore"):
        expect = np.sqrt(a)
    got = _floats(b64.sqrt(_bits(a)))
    _assert_bits_equal(got, expect, "sqrt")
    neg = _floats(b64.sqrt(_bits(np.array([-1.0, -np.inf]))))
    assert np.isnan(neg).all()


def test_neg_abs(pool):
    _assert_bits_equal(_floats(b64.neg(_bits(pool))), -pool, "neg")
    _assert_bits_equal(_floats(b64.abs_(_bits(pool))), np.abs(pool), "abs")


def test_from_i64():
    rng = np.random.default_rng(0)
    x = np.concatenate([
        rng.integers(-2 ** 62, 2 ** 62, 3000),
        rng.integers(-2 ** 53, 2 ** 53, 1000),
        np.array([0, 1, -1, 2 ** 53, 2 ** 53 + 1, -2 ** 63,
                  2 ** 63 - 1, 2 ** 62 + 12345]),
    ])
    got = _floats(b64.from_i64(jnp.asarray(x)))
    _assert_bits_equal(got, x.astype(np.float64), "from_i64")


def test_to_i64(pool):
    got = np.asarray(b64.to_i64(_bits(pool)))
    # numpy int64 cast of double is UB-ish for out-of-range: emulate Java
    expect = np.zeros(len(pool), np.int64)
    for i, v in enumerate(pool):
        if np.isnan(v):
            expect[i] = 0
        elif v >= 2.0 ** 63:
            expect[i] = 2 ** 63 - 1
        elif v <= -2.0 ** 63:
            expect[i] = -2 ** 63
        else:
            expect[i] = np.int64(np.trunc(v))
    assert (got == expect).all(), \
        np.nonzero(got != expect)[0][:5]


def test_f32_roundtrip(pool):
    f32 = pool.astype(np.float32)
    got = _floats(b64.from_f32(jnp.asarray(f32)))
    _assert_bits_equal(got, f32.astype(np.float64), "from_f32")
    narrowed = np.asarray(b64.to_f32(_bits(pool)))
    expect32 = pool.astype(np.float32)
    gb = narrowed.view(np.int32)
    eb = expect32.view(np.int32)
    ok = (gb == eb) | (np.isnan(narrowed) & np.isnan(expect32))
    assert ok.all(), [(pool[j], narrowed[j], expect32[j])
                      for j in np.nonzero(~ok)[0][:5]]


def test_rounding_ops(pool):
    with np.errstate(all="ignore"):
        _assert_bits_equal(_floats(b64.trunc(_bits(pool))), np.trunc(pool),
                           "trunc")
        _assert_bits_equal(_floats(b64.floor(_bits(pool))), np.floor(pool),
                           "floor")
        _assert_bits_equal(_floats(b64.ceil(_bits(pool))), np.ceil(pool),
                           "ceil")
        _assert_bits_equal(_floats(b64.rint(_bits(pool))), np.rint(pool),
                           "rint")


def test_order_and_compare(pool):
    a, b = pool, np.roll(pool, 5)
    ga = np.asarray(b64.lt(_bits(a), _bits(b)))
    # Spark total order: NaN greatest, NaN==NaN, -0==0
    for i in range(len(a)):
        x, y = a[i], b[i]
        if np.isnan(x):
            expect = False
        elif np.isnan(y):
            expect = True
        else:
            xx = 0.0 if x == 0 else x
            yy = 0.0 if y == 0 else y
            expect = bool(xx < yy)
        assert ga[i] == expect, (x, y, ga[i])


def test_word_roundtrip(pool):
    w = b64.order_word(_bits(pool))
    back = _floats(b64.word_to_bits(w))
    canon = np.where(np.isnan(pool), np.nan, np.where(pool == 0, 0.0, pool))
    _assert_bits_equal(back, canon.astype(np.float64), "word roundtrip")


def test_segmented_sum():
    rng = np.random.default_rng(3)
    n = 256
    vals = np.ldexp(rng.standard_normal(n), rng.integers(-30, 30, n))
    seg = np.sort(rng.integers(0, 10, n))
    mask = rng.random(n) > 0.2
    got = _floats(b64.segmented_sum(
        _bits(vals), jnp.asarray(mask), jnp.asarray(seg), 16))[:16]
    for g in range(10):
        sel = (seg == g) & mask
        expect = float(np.sum(vals[sel]))
        # float sums are association-order dependent (the scan reduces as a
        # tree); compare with relative tolerance like the reference does
        assert got[g] == pytest.approx(expect, rel=1e-12, abs=1e-300), \
            (g, got[g], expect)


def test_segmented_sum_superaccumulator_exact():
    """The windowed superaccumulator is the CORRECTLY ROUNDED exact sum
    (math.fsum) for segments whose exponent spread fits the 256-bit
    window — including cancellation, subnormal results, and ties."""
    import math
    rng = np.random.default_rng(11)
    cases = []
    # cancellation: big +x, -x, tiny residue
    cases.append([1e16, 1.0, -1e16])
    cases.append([3.0, 1e120, 2.0, -1e120])
    # subnormal results
    cases.append([5e-324, 5e-324, 5e-324])
    cases.append([2.0 ** -1074, -2.0 ** -1073, 2.0 ** -1074])
    # rounding ties (half-ulp residues)
    cases.append([2.0 ** 53, 1.0])            # tie -> even (stays 2^53)
    cases.append([2.0 ** 53, 1.0, 2.0 ** -40])  # sticky breaks the tie
    cases.append([2.0 ** 53, 3.0])
    # sub-byte residue below the 57-bit rounding window: the 1.0 lands
    # in the dropped low byte of `combined` and must reach sticky
    # (exact sum 2^63 + 1025 -> RNE up to 2^63 + 2048)
    cases.append([2.0 ** 63, 2.0 ** 10, 1.0])
    cases.append([2.0 ** 63, -(2.0 ** 10), -1.0])
    # mixed magnitudes within the window
    cases.append(list(np.ldexp(rng.standard_normal(50),
                               rng.integers(-30, 90, 50))))
    # negatives dominating
    cases.append(list(-np.ldexp(rng.random(20) + 0.5,
                                rng.integers(0, 40, 20))))
    # single elements (incl. subnormal / max finite)
    cases.append([5e-324])
    cases.append([-1.7976931348623157e308])
    # overflow to inf
    cases.append([1.7976931348623157e308, 1.7976931348623157e308])
    # specials
    cases.append([np.inf, 1.0])
    cases.append([-np.inf, 1e300])
    cases.append([np.inf, -np.inf])
    cases.append([np.nan, 1.0])
    cases.append([0.0, -0.0])
    cases.append([-0.0, -0.0])
    vals, seg = [], []
    for g, c in enumerate(cases):
        vals.extend(c)
        seg.extend([g] * len(c))
    vals = np.array(vals, np.float64)
    seg = np.array(seg, np.int32)
    n = len(vals)
    got = _floats(b64.segmented_sum(
        _bits(vals), jnp.ones(n, bool), jnp.asarray(seg), n))[:len(cases)]
    for g, c in enumerate(cases):
        finite = all(np.isfinite(v) for v in c)
        if finite:
            try:
                expect = math.fsum(c)
            except OverflowError:
                expect = math.inf if sum(c) > 0 else -math.inf
            if abs(expect) > 1.7976931348623157e308:
                expect = math.inf if expect > 0 else -math.inf
            # window contract: fsum-exact when the segment's exponent
            # spread fits the window; beyond it, error is bounded by
            # max|v| * 2^-100 (better than f64 summation in ANY order)
            amax = max(abs(v) for v in c)
            spread = (math.frexp(amax)[1] -
                      min(math.frexp(v)[1] for v in c if v != 0.0)) \
                if amax > 0 else 0
            if spread > 150:
                assert abs(got[g] - expect) <= amax * 2.0 ** -100, \
                    (g, c, float(got[g]), expect)
                continue
            if expect == 0.0:
                assert got[g] == 0.0, (g, c, got[g])
                continue
            gb = np.float64(got[g]).view(np.int64)
            eb = np.float64(expect).view(np.int64)
            assert gb == eb, (g, c, float(got[g]), expect)
        else:
            expect = np.sum(np.array(c))
            if np.isnan(expect):
                assert np.isnan(got[g]), (g, c, got[g])
            else:
                assert got[g] == expect, (g, c, got[g], expect)


def test_segmented_sum_matches_plan_bounds():
    """Plan-provided boundary arrays give the same result as derived."""
    rng = np.random.default_rng(13)
    n = 512
    vals = np.ldexp(rng.standard_normal(n), rng.integers(-40, 40, n))
    seg = np.sort(rng.integers(0, 23, n)).astype(np.int32)
    mask = rng.random(n) > 0.15
    base = _floats(b64.segmented_sum(
        _bits(vals), jnp.asarray(mask), jnp.asarray(seg), n))
    # boundary arrays computed host-side
    head = np.zeros(n, bool)
    head[0] = True
    head[1:] = seg[1:] != seg[:-1]
    hp = np.nonzero(head)[0]
    ng = len(hp)
    head_pos = np.zeros(n, np.int32)
    head_pos[:ng] = hp
    last_pos = np.zeros(n, np.int32)
    last_pos[:ng - 1] = hp[1:] - 1
    last_pos[ng - 1] = n - 1
    withp = _floats(b64.segmented_sum(
        _bits(vals), jnp.asarray(mask), jnp.asarray(seg), n,
        head_pos=jnp.asarray(head_pos), last_pos=jnp.asarray(last_pos),
        num_groups=jnp.asarray(ng)))
    _assert_bits_equal(withp[:ng], base[:ng], "plan-vs-derived bounds")


def test_running_sum():
    rng = np.random.default_rng(4)
    n = 128
    vals = rng.standard_normal(n)
    head = np.zeros(n, bool)
    head[[0, 40, 90]] = True
    got = _floats(b64.running_sum(_bits(vals), jnp.ones(n, bool),
                                  jnp.asarray(head)))
    acc = np.float64(0)
    for i in range(n):
        acc = vals[i] if head[i] else acc + vals[i]
        assert got[i] == pytest.approx(float(acc), rel=1e-12), \
            (i, got[i], acc)


def test_host_callback_transcendentals(pool):
    finite = pool[np.isfinite(pool)][:500]
    got = _floats(b64.host_unary(np.exp, _bits(finite)))
    with np.errstate(all="ignore"):
        _assert_bits_equal(got, np.exp(finite), "host exp")
