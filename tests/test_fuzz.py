"""Seeded plan/schema fuzzing — the FuzzerUtils role: random schemas
and batches (NaN, ±0.0, int extremes, epoch edges, multi-byte UTF-8,
decimals) swept through filter / cast / aggregate / join / sort on
BOTH engines, comparing rows."""
import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import dtypes as T

from harness import assert_tpu_and_cpu_are_equal_collect
from data_gen import (ALL_GENS, KeyGen, IntGen, random_schema_gens,
                      gen_df)

N_ROWS = 160
SEEDS = list(range(8))


def _numeric_cols(gens):
    return [n for n, g in gens.items()
            if g.dtype.is_integral or g.dtype.is_fractional]


def _orderable_cols(gens):
    return list(gens)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_sort(seed):
    rng = np.random.default_rng(seed)
    gens = random_schema_gens(rng)
    cols = _orderable_cols(gens)
    k = min(len(cols), 2)
    sort_cols = [cols[int(i)] for i in
                 rng.integers(0, len(cols), k)]
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, gens, N_ROWS, seed=seed)
        .order_by(*sort_cols))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_filter(seed):
    rng = np.random.default_rng(1000 + seed)
    gens = random_schema_gens(rng)
    col = list(gens)[int(rng.integers(0, len(gens)))]
    g = gens[col]
    if g.dtype.is_integral:
        thresh = int(rng.integers(-100, 100))
        pred = lambda c: (c > thresh)
    elif g.dtype.is_fractional:
        fthresh = float(rng.random() * 100)
        pred = lambda c: (c <= fthresh)
    elif g.dtype == T.BOOL:
        pred = lambda c: c
    else:
        pred = lambda c: c.is_not_null()
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, gens, N_ROWS, seed=seed)
        .filter(pred(F.col(col))))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_aggregate(seed):
    rng = np.random.default_rng(2000 + seed)
    gens = random_schema_gens(rng)
    gens["k"] = KeyGen(cardinality=7)
    nums = _numeric_cols(gens)
    aggs = [F.count("*").alias("cnt")]
    for i, c in enumerate(nums[:2]):
        aggs.append(F.sum(F.col(c)).alias(f"s{i}"))
        aggs.append(F.min(F.col(c)).alias(f"mn{i}"))
        aggs.append(F.max(F.col(c)).alias(f"mx{i}"))
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, gens, N_ROWS, seed=seed)
        .group_by("k").agg(*aggs))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_join(seed):
    rng = np.random.default_rng(3000 + seed)
    lgens = random_schema_gens(rng, n_cols=2)
    rgens = random_schema_gens(rng, n_cols=2)
    lgens["k"] = KeyGen(cardinality=12)
    rgens["k2"] = KeyGen(cardinality=12)
    how = ["inner", "left", "semi", "anti"][int(rng.integers(0, 4))]

    def run(s):
        lf = gen_df(s, lgens, N_ROWS, seed=seed)
        rf = gen_df(s, rgens, N_ROWS // 2, seed=seed + 1)
        return lf.join(rf, on=F.col("k") == F.col("k2"), how=how)
    assert_tpu_and_cpu_are_equal_collect(run)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_cast(seed):
    rng = np.random.default_rng(4000 + seed)
    # numeric <-> string/float/int cast lattice on special values
    gens = {"i": IntGen(lo=-10**6, hi=10**6),
            "f": ALL_GENS["float_no_nan"](),
            "s": KeyGen(cardinality=50)}

    def run(s):
        df = gen_df(s, gens, N_ROWS, seed=seed)
        return df.select(
            F.col("i").cast("double").alias("i2d"),
            F.col("i").cast("string").alias("i2s"),
            F.col("f").cast("long").alias("f2l"),
            F.col("s").cast("int").alias("s2i"))
    assert_tpu_and_cpu_are_equal_collect(run)
