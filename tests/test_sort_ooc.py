"""Out-of-core sort: spillable-run range merge (GpuSortExec.scala:219
third mode).  A partition whose buffered runs exceed the device budget
must complete via sliced spilled runs and match the in-core oracle."""
import numpy as np
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.memory.catalog import BufferCatalog, StorageTier
from spark_rapids_tpu.memory.spillable import SpillableBatch


def _session(chunk_rows):
    return TpuSession(TpuConf({
        "spark.rapids.tpu.sql.enabled": True,
        "spark.rapids.tpu.sql.sort.outOfCore.chunkRows": chunk_rows,
        # small scan batches -> several sorted runs per partition
        "spark.rapids.tpu.sql.batchSizeRows": 512,
        "spark.rapids.tpu.sql.reader.batchSizeRows": 512,
    }))


def test_ooc_sort_matches_oracle():
    rng = np.random.default_rng(11)
    n = 5000
    data = {
        "k": rng.integers(-1000, 1000, n).astype(np.int64),
        "s": np.array([f"v{int(x):04d}" for x in
                       rng.integers(0, 500, n)]),
        "x": rng.random(n),
    }
    s = _session(chunk_rows=700)   # total 5000 >> 700: forces OOC merge
    df = s.create_dataframe(data, num_partitions=1)
    got = df.order_by("k", "s").to_arrow()
    # oracle: numpy lexsort
    order = np.lexsort((data["s"], data["k"]))
    assert got.column("k").to_pylist() == list(data["k"][order])
    assert got.column("s").to_pylist() == list(data["s"][order])
    assert got.column("x").to_pylist() == pytest.approx(
        list(data["x"][order]))


def test_ooc_sort_desc_nulls():
    rng = np.random.default_rng(12)
    n = 3000
    k = rng.integers(0, 50, n).astype(np.int64)
    kv = [None if i % 17 == 0 else int(v) for i, v in enumerate(k)]
    s = _session(chunk_rows=400)
    df = s.create_dataframe({"k": kv, "i": np.arange(n)},
                            num_partitions=1)
    from spark_rapids_tpu.api import functions as F
    got = df.order_by(F.col("k").desc()).to_arrow()
    ks = got.column("k").to_pylist()
    nn = [v for v in ks if v is not None]
    assert nn == sorted(nn, reverse=True)
    # desc -> nulls last (Spark default)
    assert ks[-ks.count(None):].count(None) == ks.count(None)
    assert len(ks) == n


def test_ooc_sort_runs_actually_spilled():
    """The merge must read slices from HOST/DISK tier runs, not
    re-materialize whole runs (acquire_slice keeps tier)."""
    rng = np.random.default_rng(13)
    n = 4000
    s = _session(chunk_rows=600)
    # shrink the device budget so buffered runs spill while streaming
    cat = BufferCatalog.get()
    old_limit = cat.device_limit
    cat.device_limit = 1 << 14   # 16 KiB: every run must spill
    try:
        df = s.create_dataframe(
            {"k": rng.integers(0, 10**6, n).astype(np.int64)},
            num_partitions=1)
        got = df.order_by("k").to_arrow()
        assert got.column("k").to_pylist() == sorted(
            int(v) for v in df.to_arrow().column("k").to_pylist())
        assert cat.spilled_device_to_host > 0
    finally:
        cat.device_limit = old_limit


def test_acquire_slice_preserves_tier():
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar import Column, Schema, Field, dtypes as T
    from spark_rapids_tpu.columnar.column import StringColumn
    cat = BufferCatalog.reset(spill_dir="/tmp/srt_test_spill")
    vals = list(range(100))
    strs = [f"s{i:03d}" * (i % 3 + 1) for i in range(100)]
    b = ColumnarBatch(
        Schema([Field("a", T.INT64), Field("s", T.STRING)]),
        [Column.from_numpy(vals, dtype=T.INT64),
         StringColumn.from_pylist(strs)], 100)
    sb = SpillableBatch(b)
    cat.spill_device_to_fit(cat.device_limit)  # push to HOST
    e = cat._entries[sb.buffer_id]
    assert e.tier == StorageTier.HOST
    sl = sb.materialize_slice(10, 35)
    assert e.tier == StorageTier.HOST          # stayed spilled
    assert sl.num_rows == 25
    assert sl.columns[0].to_pylist(25) == vals[10:35]
    assert sl.columns[1].to_pylist(25) == strs[10:35]
    # and from DISK
    cat.host_limit = 0
    cat.spill_device_to_fit(cat.device_limit)
    for _ in range(3):
        if e.tier == StorageTier.DISK:
            break
        cat._spill_entry_to_disk(e)
    assert e.tier == StorageTier.DISK
    sl2 = sb.materialize_slice(90, 100)
    assert e.tier == StorageTier.DISK
    assert sl2.columns[1].to_pylist(10) == strs[90:100]
    sb.close()


def test_ooc_sort_duplicate_keys_still_chunks():
    """All-equal sort keys must still split into bounded chunks (the
    (run, position) tiebreaker words), not collapse to one concat."""
    n = 4000
    s = _session(chunk_rows=500)
    df = s.create_dataframe(
        {"k": np.full(n, 7, np.int64), "i": np.arange(n)},
        num_partitions=1)
    got = df.order_by("k").to_arrow()
    assert got.num_rows == n
    assert got.column("k").to_pylist() == [7] * n
    assert sorted(got.column("i").to_pylist()) == list(range(n))
