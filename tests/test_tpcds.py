"""TPC-DS query-shape equality tests: every benchmark query must give
identical results on the TPU and CPU engines at a small scale.

Reference pattern: the reference validates its TPC-DS coverage through
the same assert_gpu_and_cpu_are_equal oracle used everywhere (SURVEY.md
§4); BASELINE.json config 3 is the TPC-DS sweep.
"""
import math
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import tpcds  # noqa: E402

from harness import with_cpu_session, with_tpu_session  # noqa: E402


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcds") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


def _rows(query, data_dir):
    def fn(s):
        tpcds.register(s, data_dir)
        return s.sql(tpcds.QUERIES[query]).collect()
    return fn


def _eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        return a == b or abs(a - b) <= 1e-9 * max(abs(a), abs(b), 1.0)
    return a == b


@pytest.mark.parametrize("q", sorted(tpcds.QUERIES))
def test_tpcds_query_equality(q, data_dir):
    cpu = with_cpu_session(_rows(q, data_dir))
    tpu = with_tpu_session(_rows(q, data_dir))
    assert len(cpu) == len(tpu), f"{q}: {len(cpu)} vs {len(tpu)}"
    for i, (cr, tr) in enumerate(zip(cpu, tpu)):
        assert all(_eq(a, b) for a, b in zip(cr, tr)), \
            f"{q} row {i}: {cr} vs {tr}"
