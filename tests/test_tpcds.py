"""TPC-DS query-shape equality tests: every benchmark query must give
identical results on the TPU and CPU engines at a small scale.

Reference pattern: the reference validates its TPC-DS coverage through
the same assert_gpu_and_cpu_are_equal oracle used everywhere (SURVEY.md
§4); BASELINE.json config 3 is the TPC-DS sweep.
"""
import math
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import tpcds  # noqa: E402

from harness import with_cpu_session, with_tpu_session  # noqa: E402


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcds") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


def _rows(query, data_dir):
    def fn(s):
        tpcds.register(s, data_dir)
        return s.sql(tpcds.QUERIES[query]).collect()
    return fn


def _eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        return a == b or abs(a - b) <= 1e-9 * max(abs(a), abs(b), 1.0)
    return a == b


def _canon(rows):
    """Most TPC-DS ORDER BYs do not fully determine the output (ties),
    so engines may legally differ within tie groups — compare the
    sorted multiset (the reference harness's ignore_order)."""
    from harness import canon_rows
    return canon_rows(rows)


#: running 99 queries x 2 engines in ONE process accumulates thousands
#: of XLA:CPU executables; past a threshold LLVM's JIT code memory
#: segfaults on the next compile (observed deterministically at the
#: 88th query).  Dropping the executable caches every 25 queries keeps
#: the arena bounded; re-compiles at the 16-row test sizes are cheap.
_QUERIES_RUN = {"n": 0}


@pytest.fixture(autouse=True)
def _bounded_compile_arena():
    yield
    _QUERIES_RUN["n"] += 1
    if _QUERIES_RUN["n"] % 25 == 0:
        from spark_rapids_tpu.shims.compile_caches import \
            clear_compile_caches
        clear_compile_caches()


@pytest.mark.parametrize("q", sorted(tpcds.QUERIES))
def test_tpcds_query_equality(q, data_dir):
    cpu = _canon(with_cpu_session(_rows(q, data_dir)))
    tpu = _canon(with_tpu_session(_rows(q, data_dir)))
    assert len(cpu) == len(tpu), f"{q}: {len(cpu)} vs {len(tpu)}"
    for i, (cr, tr) in enumerate(zip(cpu, tpu)):
        assert all(_eq(a, b) for a, b in zip(cr, tr)), \
            f"{q} row {i}: {cr} vs {tr}"
