"""Cross-plane query doctor tests (obs/doctor.py): exactly-one primary
bottleneck with contribution shares summing to 100, Amdahl headroom
bounds consistent with the timeline's gap shares, the ranked ROADMAP
mapping, digest stability across pipeline parallelism {1,4} x
superstage on/off, the event-log / Prometheus / stats / report
surfaces, the bench-record adapter behind ci/perf_gate.py, and the
zero-extra-flush + disabled-plane acceptance contracts."""
import json
import os

import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar import pending
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.obs import doctor
from spark_rapids_tpu.obs.prom import render_text
from spark_rapids_tpu.obs.registry import TIMELINE_GAP_CAUSES

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _doctor_reset():
    doctor.configure(TpuConf({}))
    doctor.reset()
    yield
    doctor.configure(TpuConf({}))
    doctor.reset()


def _tl(util, **gaps):
    g = {c: 0.0 for c in TIMELINE_GAP_CAUSES}
    g.update(gaps)
    return {"busy_ms": util, "window_ms": 100.0, "util_pct": util,
            "gaps": g}


def _agg_join_df(sess, n=50_000, groups=31):
    df = sess.range(0, n, 1, 4)
    df = df.with_column("k", df["id"] % groups)
    dim = sess.range(0, groups, 1, 1).with_column("v", F.col("id") * 2)
    j = df.join(dim.with_column_renamed("id", "k2"),
                df["k"] == F.col("k2"), "inner")
    return j.group_by("k").agg(F.sum("v").alias("sv"))


# ---------------------------------------------------------------------------
# 1. verdict model
# ---------------------------------------------------------------------------

class TestVerdictModel:
    def test_exactly_one_primary_and_sum_to_100(self):
        d = doctor.diagnose(_tl(40.0, shuffle_host=23.655,
                                mem_spill=10.0, host_staging=21.345,
                                inline_compile=5.0))
        assert d.primary_cause == "device_compute"
        # a partition of the window: exactly 100 to float epsilon
        assert sum(d.data["shares"].values()) == pytest.approx(
            100.0, abs=1e-6)
        # exactly ONE cause carries the primary verdict
        top = [c for c, v in d.data["shares"].items()
               if v == max(d.data["shares"].values())]
        assert d.primary_cause in top

    def test_amdahl_bound_matches_gap_share(self):
        # the ISSUE's worked example: a 23.655% shuffle_host share
        # bounds speedup at 1/(1-0.23655) = 1.31x
        d = doctor.diagnose(_tl(40.0, shuffle_host=23.655,
                                mem_spill=10.0, host_staging=26.345))
        by = {c["cause"]: c for c in d.headroom}
        assert by["shuffle_host"]["bound_x"] == pytest.approx(1.31,
                                                              abs=0.005)
        # the bound rule holds for EVERY candidate, which is what
        # makes the headroom table consistent with the gap shares
        for c in d.headroom:
            assert c["bound_x"] == pytest.approx(
                1.0 / (1.0 - c["share_pct"] / 100.0), rel=1e-3)

    def test_deterministic_tie_break_by_taxonomy_order(self):
        # two equal shares: device_compute outranks host_staging in
        # the fixed priority order, never dict order
        d = doctor.diagnose(_tl(50.0, host_staging=50.0))
        assert d.primary_cause == "device_compute"
        d2 = doctor.diagnose(_tl(0.0, shuffle_host=50.0, mem_spill=50.0))
        assert d2.primary_cause == "shuffle_host"

    def test_roadmap_mapping_is_ranked_and_complete(self):
        d = doctor.diagnose(_tl(10.0, shuffle_host=40.0,
                                inline_compile=30.0, mem_spill=20.0))
        assert d.primary_cause == "shuffle_host"
        # ranked by share, every candidate mapped onto items 1-4
        shares = [c["share_pct"] for c in d.headroom]
        assert shares == sorted(shares, reverse=True)
        for c in d.headroom:
            assert c["roadmap_item"] in (1, 2, 3, 4)
            assert c["fix"]
        assert d.headroom[0]["roadmap_item"] == 1       # ICI shuffle
        by = {c["cause"]: c["roadmap_item"] for c in d.headroom}
        assert by["inline_compile"] == 3 and by["mem_spill"] == 2

    def test_rounding_residue_folded_to_exactly_100(self):
        # 3-decimal timeline rounding leaves a residue; the doctor
        # folds it into the largest component
        d = doctor.diagnose(_tl(33.333, host_staging=33.333,
                                shuffle_host=33.333))
        assert sum(d.data["shares"].values()) == pytest.approx(
            100.0, abs=1e-9)

    def test_empty_window_degrades_to_host_staging(self):
        d = doctor.diagnose(_tl(0.0))
        assert d.primary_cause == "host_staging"
        assert sum(d.data["shares"].values()) == pytest.approx(100.0)

    def test_evidence_cites_owning_plane(self):
        d = doctor.diagnose(
            _tl(30.0, shuffle_host=40.0, mem_spill=20.0,
                inline_compile=10.0),
            inline_compile_ms=12.5,
            netplane={"host_drop_tax_ms": 8.1, "edge_skew": 1.4,
                      "edges": 3},
            memplane={"spill_ms": 6.0, "peak_device_bytes": 4096,
                      "spill": {"device_to_host": {"count": 2}}},
            flushes=3, predicted_flushes=3)
        by = {c["cause"]: c["evidence"] for c in d.headroom}
        assert "host_drop_tax_ms=8.1" in by["shuffle_host"]
        assert "spill_ms=6.0" in by["mem_spill"]
        assert "2 tier moves" in by["mem_spill"]
        assert "inline_compile_ms=12.5" in by["inline_compile"]
        assert "flushes=3" in by["device_compute"]

    def test_verdict_line_names_bound_and_roadmap_item(self):
        d = doctor.diagnose(_tl(20.0, shuffle_host=23.655,
                                host_staging=56.345))
        line = d.verdict_line()
        assert "host_staging" in line and "ROADMAP item 4" in line

    def test_verdict_counter_and_stats_section(self):
        doctor.diagnose(_tl(10.0, shuffle_host=90.0))
        doctor.diagnose(_tl(10.0, shuffle_host=90.0))
        doctor.diagnose(_tl(90.0, shuffle_host=10.0))
        sec = doctor.stats_section()
        assert sec["verdicts"]["shuffle_host"] == 2
        assert sec["verdicts"]["device_compute"] == 1
        assert sec["last"]["primary_cause"] == "device_compute"
        text = render_text()
        assert 'tpu_doctor_verdicts_total{cause="shuffle_host"}' in text


# ---------------------------------------------------------------------------
# 2. bench-record adapter (the perf gate's verdict printer)
# ---------------------------------------------------------------------------

class TestBenchAdapter:
    def test_diagnose_bench_on_current_round(self):
        from spark_rapids_tpu.analysis import regression as R
        rec = R.load_round(os.path.join(REPO_ROOT,
                                        "BENCH_r12.json")).keys
        d = doctor.diagnose_bench(rec)
        assert d is not None
        assert sum(d.data["shares"].values()) == pytest.approx(100.0)
        assert d.primary_cause == rec["doctor_primary_cause"]

    def test_diagnose_bench_none_on_pre_timeline_round(self):
        from spark_rapids_tpu.analysis import regression as R
        rec = R.load_round(os.path.join(REPO_ROOT,
                                        "BENCH_r05.json")).keys
        assert doctor.diagnose_bench(rec) is None


# ---------------------------------------------------------------------------
# 3. end-to-end acceptance contracts
# ---------------------------------------------------------------------------

class TestEndToEnd:
    def test_session_surfaces_one_verdict(self):
        s = TpuSession(TpuConf({}))
        df = _agg_join_df(s)
        df.collect()
        df.collect()
        d = s.last_query_diagnosis
        assert d is not None
        assert d.primary_cause in d.data["shares"]
        assert sum(d.data["shares"].values()) == pytest.approx(
            100.0, abs=1e-6)
        # headroom bounds consistent with the timeline's gap shares:
        # every gap cause with a nonzero share appears with exactly
        # the Amdahl bound of (approximately) that share
        tl = s.last_query_timeline
        by = {c["cause"]: c for c in d.headroom}
        for cause, share in tl["gaps"].items():
            if share <= 0:
                continue
            cand = by[cause]
            assert cand["share_pct"] == pytest.approx(share, abs=0.01)
            assert cand["bound_x"] == pytest.approx(
                1.0 / (1.0 - cand["share_pct"] / 100.0), rel=1e-3)

    def test_digest_stable_across_parallelism_and_superstage(self):
        digests = {}
        for par in (1, 4):
            for stage in (True, False):
                s = TpuSession(TpuConf({
                    "spark.rapids.tpu.exec.pipelineParallelism": par,
                    "spark.rapids.tpu.sql.superstage": stage}))
                df = _agg_join_df(s)
                df.collect()
                df.collect()
                d = s.last_query_diagnosis
                assert d is not None
                # exactly-one primary, sum-to-100: per-config
                assert d.primary_cause in d.data["shares"]
                assert sum(d.data["shares"].values()) == pytest.approx(
                    100.0, abs=1e-6)
                digests[(par, stage)] = d.stable_digest()
        # the cause+headroom digest (verdict model keyed by the
        # query's data identity) must not move with execution config
        assert len(set(digests.values())) == 1, digests

    def test_doctor_adds_zero_flushes(self):
        def measure(enabled):
            s = TpuSession(TpuConf({
                "spark.rapids.tpu.obs.doctor.enabled": enabled}))
            df = _agg_join_df(s)
            df.collect()                       # warm
            f0 = pending.FLUSH_COUNT
            df.collect()
            return pending.FLUSH_COUNT - f0, s.last_query_diagnosis
        flushes_on, diag_on = measure(True)
        flushes_off, diag_off = measure(False)
        assert diag_on is not None and diag_off is None
        # the acceptance contract: an EXACT device round-trip match
        assert flushes_on == flushes_off

    def test_disabled_plane_is_a_noop(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        doctor.reset()
        s = TpuSession(TpuConf({
            "spark.rapids.tpu.eventLog.path": log,
            "spark.rapids.tpu.obs.doctor.enabled": False}))
        _agg_join_df(s).collect()
        assert s.last_query_diagnosis is None
        assert doctor.stats_section()["verdicts"] == {}
        recs = [json.loads(ln) for ln in open(log)]
        assert all("doctor" not in r for r in recs)

    def test_event_log_and_report_carry_verdict(self, tmp_path):
        from spark_rapids_tpu.tools.report import (doctor_lines,
                                                   load_query_stories,
                                                   render_report)
        log = str(tmp_path / "events.jsonl")
        s = TpuSession(TpuConf({"spark.rapids.tpu.eventLog.path": log}))
        df = _agg_join_df(s)
        df.collect()
        df.collect()
        recs = [json.loads(ln) for ln in open(log)]
        doc = next(r["doctor"] for r in recs if "doctor" in r)
        assert doc["primary_cause"] == \
            s.last_query_diagnosis.primary_cause
        assert sum(doc["shares"].values()) == pytest.approx(
            100.0, abs=1e-6)
        stories = load_query_stories(log)
        txt = render_report(stories, show_doctor=True)
        assert "query doctor (cross-plane verdict)" in txt
        assert "primary bottleneck" in txt
        assert "Amdahl" in txt

    def test_service_stats_carry_doctor_section(self):
        from spark_rapids_tpu.service import QueryService
        s = TpuSession(TpuConf({}))
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(s.range(0, 100, num_partitions=1),
                           tenant="doc")
            h.result(timeout=120)
            snap = svc.stats().snapshot()
        assert "doctor" in snap
        assert snap["doctor"]["enabled"] is True
        assert sum(snap["doctor"]["verdicts"].values()) >= 1


# ---------------------------------------------------------------------------
# 4. TPC-DS quartet (the acceptance sweep; mirrored in
#    ci/compile_smoke.py for the CI gate)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tpcds_quartet_one_verdict_each(tmp_path):
    from benchmarks import tpcds
    data_dir = str(tmp_path / "tpcds")
    tpcds.generate(data_dir, scale=0.002, seed=11)
    s = TpuSession(TpuConf({}))
    tpcds.register(s, data_dir)
    for q in ("q3", "q42", "q52", "q96"):
        df = s.sql(tpcds.QUERIES[q])
        df.collect()
        df.collect()
        d = s.last_query_diagnosis
        assert d is not None, q
        assert sum(d.data["shares"].values()) == pytest.approx(
            100.0, abs=1e-6), q
        tl = s.last_query_timeline
        by = {c["cause"]: c for c in d.headroom}
        for cause, share in tl["gaps"].items():
            if share <= 0:
                continue
            assert by[cause]["bound_x"] == pytest.approx(
                1.0 / (1.0 - by[cause]["share_pct"] / 100.0),
                rel=1e-3), (q, cause)
