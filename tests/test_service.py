"""Concurrent query service tests.

Covers the serving subsystem end to end on the virtual CPU mesh:
admission control + fair queueing + load shedding, per-query deadlines
and cooperative cancellation (with resource release back to baseline),
device-OOM retry with batch degradation, thread-safe conf/session
activation, the per-query semaphore-wait metric, stable query_id across
the event log, and the multi-tenant stress acceptance test.
"""
import threading
import time
import types

import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import dtypes as T
from spark_rapids_tpu.config import (
    TpuConf, get_active, BATCH_SIZE_ROWS, BATCH_SIZE_BYTES)
from spark_rapids_tpu.memory.arena import DeviceManager, DeviceSemaphore
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.service import (
    CancelToken, QueryCancelledError, ServiceOverloaded, QueryService,
    cancel_checkpoint, query_context)
from spark_rapids_tpu.service.queue import FairQueryQueue
from spark_rapids_tpu.service.retry import RetryPolicy
from spark_rapids_tpu.tools.events import read_event_log
from spark_rapids_tpu.udf import pandas_udf


def _item(tenant, priority, est_bytes=0, tag=None):
    return types.SimpleNamespace(tenant=tenant, priority=priority,
                                 est_bytes=est_bytes, tag=tag)


def _tpu_session(extra=None):
    settings = {"spark.rapids.tpu.sql.enabled": True,
                "spark.rapids.tpu.sql.shuffle.partitions": 4}
    settings.update(extra or {})
    return TpuSession(TpuConf(settings))


def _rows(table):
    return sorted(tuple(r.values()) for r in table.to_pylist())


def _drain_semaphore():
    """Every permit must be takeable => nothing leaked a hold."""
    sem = DeviceManager.get().semaphore
    got = [sem._sem.acquire(blocking=False) for _ in range(sem.permits)]
    for ok in got:
        if ok:
            sem._sem.release()
    return all(got)


# ---------------------------------------------------------------------------
# unit: fair queue
# ---------------------------------------------------------------------------

class TestFairQueue:
    def test_depth_shedding(self):
        q = FairQueryQueue(max_depth=2)
        q.offer(_item("a", 0))
        q.offer(_item("a", 0))
        with pytest.raises(ServiceOverloaded) as ei:
            q.offer(_item("a", 0))
        assert ei.value.queue_depth == 2
        assert ei.value.max_depth == 2

    def test_bytes_shedding(self):
        q = FairQueryQueue(max_depth=10, max_bytes=100)
        q.offer(_item("a", 0, est_bytes=60))
        with pytest.raises(ServiceOverloaded):
            q.offer(_item("a", 0, est_bytes=50))
        # a small one still fits
        q.offer(_item("b", 0, est_bytes=40))
        assert q.stats()["queued_bytes"] == 100

    def test_priority_then_tenant_round_robin(self):
        q = FairQueryQueue(max_depth=16)
        for tag in ("a1", "a2", "a3"):
            q.offer(_item("A", 0, tag=tag))
        for tag in ("b1", "b2"):
            q.offer(_item("B", 0, tag=tag))
        q.offer(_item("C", 5, tag="hi"))
        order = [q.take(0.1).tag for _ in range(6)]
        # strict priority first, then A/B alternate, FIFO within tenant
        assert order[0] == "hi"
        assert order[1:] == ["a1", "b1", "a2", "b2", "a3"]

    def test_remove_and_close(self):
        q = FairQueryQueue(max_depth=4)
        it = _item("a", 0, tag="x")
        q.offer(it)
        assert q.remove(it) is True
        assert q.remove(it) is False
        assert q.stats()["depth"] == 0
        q.close()
        assert q.take(0.1) is None
        with pytest.raises(ServiceOverloaded):
            q.offer(_item("a", 0))


# ---------------------------------------------------------------------------
# unit: retry policy + cancel token + semaphore integration
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_classification(self):
        from spark_rapids_tpu.shuffle.iterator import ShuffleFetchFailedError
        p = RetryPolicy()
        oom = RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
        assert p.is_retryable(oom)
        assert p.classify(oom) == "device_oom"
        fetch = ShuffleFetchFailedError(None, "peer gone")
        assert p.is_retryable(fetch)
        assert p.classify(fetch) == "shuffle_fetch_failed"
        assert not p.is_retryable(ValueError("nope"))
        assert p.classify(ValueError("nope")) == "fatal"

    def test_backoff_and_overlay(self):
        p = RetryPolicy(max_attempts=4, backoff_ms=10, multiplier=2.0,
                        batch_decay=0.5)
        assert p.backoff_s(1) == pytest.approx(0.010)
        assert p.backoff_s(3) == pytest.approx(0.040)
        base = TpuConf({BATCH_SIZE_ROWS.key: 4096})
        assert p.overlay(0, base) == {}
        o1 = p.overlay(1, base)
        assert o1[BATCH_SIZE_ROWS.key] == 2048
        # floors hold: decay never goes below the minimum batch
        o9 = p.overlay(9, base)
        assert o9[BATCH_SIZE_ROWS.key] == 256
        assert o9[BATCH_SIZE_BYTES.key] == 1 << 20


class TestCancelToken:
    def test_deadline_auto_cancel(self):
        tok = CancelToken("q1", deadline=time.monotonic() + 0.05)
        assert not tok.cancelled
        time.sleep(0.08)
        assert tok.cancelled
        assert tok.reason == "deadline"
        with pytest.raises(QueryCancelledError):
            tok.check()

    def test_checkpoint_only_fires_inside_context(self):
        cancel_checkpoint()          # no active query: must be a no-op
        tok = CancelToken("q2")
        tok.cancel("cancelled")
        with query_context(tok):
            with pytest.raises(QueryCancelledError):
                cancel_checkpoint()
        cancel_checkpoint()          # context restored

    def test_wait_cancelled_interrupts(self):
        tok = CancelToken("q3")
        threading.Timer(0.05, tok.cancel).start()
        t0 = time.monotonic()
        assert tok.wait_cancelled(5.0) is True
        assert time.monotonic() - t0 < 1.0

    def test_semaphore_wait_is_cancellable_and_accounted(self):
        sem = DeviceSemaphore(1)
        holder_ready = threading.Event()
        release = threading.Event()

        def hold():
            sem.acquire_if_necessary()
            holder_ready.set()
            release.wait(10)
            sem.release()

        t = threading.Thread(target=hold)
        t.start()
        holder_ready.wait(10)
        # a cancelled query blocked on the semaphore unwinds promptly
        tok = CancelToken("q4", deadline=time.monotonic() + 0.1)
        sem.pop_wait_ns()
        with query_context(tok):
            with pytest.raises(QueryCancelledError):
                sem.acquire_if_necessary()
        assert sem.pop_wait_ns() > 0       # blocked time was recorded
        release.set()
        t.join(10)
        assert sem.held_count() == 0
        assert _drain_semaphore()


# ---------------------------------------------------------------------------
# satellite: thread-safe conf/session activation
# ---------------------------------------------------------------------------

class TestActiveConfThreadSafety:
    def test_two_threads_do_not_cross_observe_confs(self):
        rows_a, rows_b = 111, 222
        barrier = threading.Barrier(2, timeout=30)
        errors = []

        def client(batch_rows, results):
            try:
                s = _tpu_session({BATCH_SIZE_ROWS.key: batch_rows})
                barrier.wait()
                for _ in range(5):
                    assert get_active().get(BATCH_SIZE_ROWS) == batch_rows
                    assert TpuSession.active() is s
                    got = s.range(0, 100, num_partitions=2) \
                        .filter(F.col("id") % 9 == 0).collect()
                    assert sorted(v for v, in got) == list(range(0, 100, 9))
                    assert get_active().get(BATCH_SIZE_ROWS) == batch_rows
                    assert TpuSession.active() is s
                results.append(s)
            except Exception as e:       # noqa: BLE001 - surfaced below
                errors.append(e)
                try:
                    barrier.abort()
                except Exception:
                    pass

        res_a, res_b = [], []
        ta = threading.Thread(target=client, args=(rows_a, res_a))
        tb = threading.Thread(target=client, args=(rows_b, res_b))
        ta.start(); tb.start()
        ta.join(60); tb.join(60)
        assert not errors, errors
        assert res_a and res_b and res_a[0] is not res_b[0]


# ---------------------------------------------------------------------------
# service: basic completion, shedding, deadlines, cancellation
# ---------------------------------------------------------------------------

class TestServiceBasic:
    def test_concurrent_queries_row_exact(self):
        s = _tpu_session()
        expected = sorted((v,) for v in range(1000) if v % 7 == 0)
        with QueryService(s, num_workers=3) as svc:
            handles = [svc.submit(
                s.range(0, 1000, num_partitions=2)
                .filter(F.col("id") % 7 == 0),
                tenant=f"t{i % 3}", priority=i % 2)
                for i in range(9)]
            for h in handles:
                assert _rows(h.result(timeout=120)) == expected
                assert h.status == "DONE"
        snap = svc.snapshot()
        assert snap["submitted"] == snap["admitted"] == 9
        assert snap["completed"] == 9
        assert snap["shed"] == snap["failed"] == snap["cancelled"] == 0
        assert snap["inflight"] == 0 and snap["depth"] == 0

    def test_sql_and_dataframe_submission(self):
        s = _tpu_session()
        df = s.create_dataframe(
            {"k": [1, 2, 1, 2], "v": [10, 20, 30, 40]})
        s.register_table("tv", df)
        with QueryService(s, num_workers=2) as svc:
            h_sql = svc.submit("SELECT k, SUM(v) AS sv FROM tv GROUP BY k")
            h_df = svc.submit(df.group_by("k").agg(F.sum("v").alias("sv")))
            assert _rows(h_sql.result(60)) == [(1, 40), (2, 60)]
            assert _rows(h_df.result(60)) == [(1, 40), (2, 60)]
        with pytest.raises(TypeError):
            QueryService(s)._to_logical(12345)

    def test_load_shedding_when_saturated(self):
        s = _tpu_session()
        gate = threading.Event()
        started = threading.Event()

        def _blocked(series):
            started.set()
            gate.wait(30)
            return series
        blocker = pandas_udf(_blocked, return_type=T.INT64)
        df_slow = s.range(0, 8).select(blocker(F.col("id")).alias("id"))
        df_fast = s.range(0, 8)
        svc = QueryService(
            s, num_workers=1)
        svc.queue = FairQueryQueue(max_depth=1)
        svc.start()
        try:
            h_run = svc.submit(df_slow, tenant="slow")
            assert started.wait(30)          # worker is now busy
            h_q = svc.submit(df_fast, tenant="fast")     # fills the queue
            with pytest.raises(ServiceOverloaded):
                svc.submit(df_fast, tenant="fast")       # shed
            gate.set()
            assert h_run.result(60).num_rows == 8
            assert h_q.result(60).num_rows == 8
        finally:
            gate.set()
            svc.shutdown(wait=True, timeout=30)
        snap = svc.snapshot()
        assert snap["shed"] == 1
        assert snap["completed"] == 2


class TestDeadlinesAndCancellation:
    def _slow_df(self, s, started=None, sleep_s=0.05):
        def _slow(series):
            if started is not None:
                started.set()
            time.sleep(sleep_s)
            return series
        slow = pandas_udf(_slow, return_type=T.INT64)
        return s.create_dataframe(
            {"k": [i % 4 for i in range(64)],
             "v": list(range(64))}, num_partitions=2) \
            .group_by("k").agg(F.sum("v").alias("sv")) \
            .select(F.col("k"), slow(F.col("sv")).alias("sv"))

    def test_deadline_exceeded_reports_cancelled(self):
        s = _tpu_session()
        with QueryService(s, num_workers=2) as svc:
            h = svc.submit(self._slow_df(s), tenant="dl", deadline_ms=60)
            t0 = time.monotonic()
            with pytest.raises(QueryCancelledError) as ei:
                h.result(timeout=60)        # bounded: no deadlock
            assert time.monotonic() - t0 < 30
            assert ei.value.reason == "deadline"
            assert h.status == "CANCELLED"
            assert h.metrics.outcome == "cancelled"
        assert svc.snapshot()["deadline_exceeded"] == 1

    def test_cancel_while_queued(self):
        s = _tpu_session()
        gate = threading.Event()
        started = threading.Event()

        def _blocked(series):
            started.set()
            gate.wait(30)
            return series
        blocker = pandas_udf(_blocked, return_type=T.INT64)
        svc = QueryService(s, num_workers=1).start()
        try:
            h_run = svc.submit(
                s.range(0, 8).select(blocker(F.col("id")).alias("id")))
            assert started.wait(30)
            h_q = svc.submit(s.range(0, 8))
            assert h_q.cancel() is True
            with pytest.raises(QueryCancelledError):
                h_q.result(timeout=10)
            assert h_q.status == "CANCELLED"
            gate.set()
            assert h_run.result(60).num_rows == 8
        finally:
            gate.set()
            svc.shutdown(wait=True, timeout=30)

    def test_mid_execution_cancel_releases_resources(self):
        s = _tpu_session()
        cat = BufferCatalog.get()
        # settle baseline with one warmup through the service
        with QueryService(s, num_workers=1) as warm:
            warm.submit(self._slow_df(s, sleep_s=0.0)).result(60)
        base_bytes = cat.device_bytes
        base_entries = len(cat._entries)

        started = threading.Event()
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(self._slow_df(s, started=started, sleep_s=0.1),
                           tenant="victim")
            assert started.wait(30)          # mid-execution now
            assert h.cancel("cancelled") is True
            t0 = time.monotonic()
            with pytest.raises(QueryCancelledError) as ei:
                h.result(timeout=60)
            assert time.monotonic() - t0 < 30     # unwound, no deadlock
            assert ei.value.reason == "cancelled"
            assert h.status == "CANCELLED"
        # arena back to baseline: no leaked catalog buffers, no held
        # semaphore permits, no orphaned shuffle map outputs
        assert cat.device_bytes == base_bytes
        assert len(cat._entries) == base_entries
        assert _drain_semaphore()
        assert not h.token.pop_owned_buffers()
        assert not h.token.pop_owned_shuffles()


# ---------------------------------------------------------------------------
# retry + event log: stable query_id, sem-wait metric, OOM degradation
# ---------------------------------------------------------------------------

class TestRetryAndEventLog:
    def test_oom_retry_succeeds_with_stable_query_id(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        s = _tpu_session({"spark.rapids.tpu.eventLog.path": log,
                          "spark.rapids.tpu.service.retry"
                          ".initialBackoffMs": 5})
        calls = {"n": 0}

        def _flaky(series):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: injected test OOM")
            return series
        flaky = pandas_udf(_flaky, return_type=T.INT64)
        df = s.create_dataframe(
            {"k": [1, 2, 1, 2], "v": [5, 6, 7, 8]}) \
            .group_by("k").agg(F.sum("v").alias("sv")) \
            .select(F.col("k"), flaky(F.col("sv")).alias("sv"))
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(df, tenant="flaky")
            assert _rows(h.result(120)) == [(1, 12), (2, 14)]
        assert h.metrics.attempts == 2
        assert h.metrics.retries == 1
        assert svc.snapshot()["retries"] == 1

        recs = read_event_log(log, events=None)
        mine = [r for r in recs if r.get("query_id") == h.query_id]
        kinds = [r["event"] for r in mine]
        # one stable id joins admission -> retry -> engine runs -> outcome
        assert kinds.count("admitted") == 1
        assert kinds.count("retry") == 1
        assert kinds.count("completed") == 1
        assert kinds.count("query") >= 1     # attempt 2's engine record
        retry_rec = next(r for r in mine if r["event"] == "retry")
        assert retry_rec["reason"] == "device_oom"
        # the retry attempt ran degraded: smaller batch-size overlay
        overlay = retry_rec["conf_overlay"]
        assert overlay[BATCH_SIZE_ROWS.key] < \
            s.conf.get(BATCH_SIZE_ROWS)
        done_rec = next(r for r in mine if r["event"] == "completed")
        assert done_rec["outcome"] == "completed"
        assert done_rec["attempts"] == 2
        for key in ("queue_wait_ms", "sem_wait_ms", "execute_ms",
                    "spill_bytes"):
            assert key in done_rec
        # engine records carry the per-query device metrics too
        for r in mine:
            if r["event"] == "query":
                assert "sem_wait_ms" in r and "spill_bytes" in r

    def test_fatal_error_not_retried(self):
        s = _tpu_session()

        def _boom(series):
            raise ValueError("schema drift: not retryable")
        boom = pandas_udf(_boom, return_type=T.INT64)
        df = s.range(0, 8).select(boom(F.col("id")).alias("id"))
        with QueryService(s, num_workers=1) as svc:
            h = svc.submit(df)
            with pytest.raises(ValueError):
                h.result(60)
        assert h.status == "FAILED"
        assert h.metrics.attempts == 1
        assert svc.snapshot()["retries"] == 0
        assert svc.snapshot()["failed"] == 1

    def test_default_event_log_read_hides_service_lines(self, tmp_path):
        log = str(tmp_path / "events.jsonl")
        s = _tpu_session({"spark.rapids.tpu.eventLog.path": log})
        with QueryService(s, num_workers=1) as svc:
            svc.submit(s.range(0, 16)).result(60)
        engine_only = read_event_log(log)
        assert engine_only and all(
            r["event"] == "query" for r in engine_only)
        everything = read_event_log(log, events=None)
        assert {"admitted", "completed", "query"} <= {
            r["event"] for r in everything}


# ---------------------------------------------------------------------------
# acceptance: multi-tenant stress under a spill-forcing arena budget
# ---------------------------------------------------------------------------

class TestServiceStress:
    N_CLIENTS = 8
    PER_CLIENT = 7          # 56 queries total

    N_ROWS = 600

    def _expected_groupby(self, client):
        sums = {}
        for i in range(self.N_ROWS):
            sums[i % 5] = sums.get(i % 5, 0) + (i + client)
        return sorted(sums.items())

    def test_stress_multi_tenant_spill_deadlines_no_leaks(self):
        s = _tpu_session({
            "spark.rapids.tpu.sql.concurrentTpuTasks": 2,
            # several sorted runs per partition + ooc merge: the sort
            # shape below must go through the spillable-run path
            "spark.rapids.tpu.sql.batchSizeRows": 512,
            "spark.rapids.tpu.sql.reader.batchSizeRows": 512,
            "spark.rapids.tpu.sql.sort.outOfCore.chunkRows": 600,
            # tight latency target: the slow tenant + the deadline pair
            # must show up as attributed SLO breaches (obs/slo.py)
            "spark.rapids.tpu.obs.slo.targetMs": 50})
        from spark_rapids_tpu.obs import slo as _slo_mod
        _slo_mod.reset()   # isolate tenant accounting from other tests
        cat = BufferCatalog.get()
        base_bytes = cat.device_bytes
        base_entries = len(cat._entries)
        spill0 = cat.spilled_device_to_host + cat.spilled_host_to_disk

        def _slow(series):
            time.sleep(0.02)
            return series
        slow = pandas_udf(_slow, return_type=T.INT64)

        def make_df(client, j):
            if j == 0:
                # out-of-core sort: 4000 rows >> chunkRows under a
                # 16 KiB device budget — buffered runs must spill
                vals = [(i * 2654435761 + client) % 100003
                        for i in range(4000)]
                return (s.create_dataframe({"k": vals}, num_partitions=1)
                        .order_by("k"),
                        sorted((v,) for v in vals))
            data = {"k": [i % 5 for i in range(self.N_ROWS)],
                    "v": [i + client for i in range(self.N_ROWS)]}
            if j % 2 == 0:
                df = s.create_dataframe(data, num_partitions=2) \
                    .group_by("k").agg(F.sum("v").alias("sv")) \
                    .order_by("k")
                if client == 0:       # the artificially slow tenant
                    df = df.select(F.col("k"),
                                   slow(F.col("sv")).alias("sv"))
                return df, self._expected_groupby(client)
            lo, hi = client * 10, client * 10 + 300
            return (s.range(lo, hi, num_partitions=2)
                    .filter(F.col("id") % 11 == 0),
                    sorted((v,) for v in range(lo, hi) if v % 11 == 0))

        old_limit = cat.device_limit
        cat.device_limit = 1 << 14        # tiny budget: force spilling
        errors = []
        deadline_handles = []
        try:
            with QueryService(s, num_workers=4) as svc:
                def client_thread(client):
                    try:
                        pairs = [make_df(client, j)
                                 for j in range(self.PER_CLIENT)]
                        handles = [
                            (svc.submit(df, tenant=f"tenant{client}",
                                        priority=j % 2), exp)
                            for j, (df, exp) in enumerate(pairs)]
                        for h, exp in handles:
                            got = _rows(h.result(timeout=300))
                            assert got == [tuple(e) if isinstance(e, tuple)
                                           else e for e in exp] or \
                                got == list(exp), \
                                f"client {client}: wrong rows"
                    except Exception as e:   # noqa: BLE001
                        errors.append((client, e))

                threads = [threading.Thread(target=client_thread, args=(c,))
                           for c in range(self.N_CLIENTS)]
                t0 = time.monotonic()
                for t in threads:
                    t.start()
                # two doomed queries: deadline far shorter than the slow
                # tenant's execution; they must report CANCELLED, not hang
                for _ in range(2):
                    df, _exp = make_df(0, 0)
                    deadline_handles.append(
                        svc.submit(df, tenant="tenant0", deadline_ms=1))
                for t in threads:
                    t.join(600)
                    assert not t.is_alive(), "client thread hung"
                for h in deadline_handles:
                    with pytest.raises(QueryCancelledError):
                        h.result(timeout=60)
                    assert h.status == "CANCELLED"
                wall = time.monotonic() - t0
                assert wall < 500, f"stress took {wall:.0f}s"
        finally:
            cat.device_limit = old_limit
        assert not errors, errors

        snap = svc.snapshot()
        total = self.N_CLIENTS * self.PER_CLIENT + 2
        assert snap["submitted"] == total
        assert snap["completed"] == self.N_CLIENTS * self.PER_CLIENT
        assert snap["cancelled"] == 2
        assert snap["deadline_exceeded"] == 2
        assert snap["inflight"] == 0 and snap["depth"] == 0
        # the tiny arena budget really exercised the spill path
        spilled = (cat.spilled_device_to_host +
                   cat.spilled_host_to_disk) - spill0
        assert spilled > 0
        # zero leaks at shutdown: permits takeable, catalog at baseline
        assert _drain_semaphore()
        assert cat.device_bytes == base_bytes
        assert len(cat._entries) == base_entries

        # per-tenant SLO plane (obs/slo.py): every tenant has ordered
        # percentiles, every breach is attributed to exactly one cause
        slo = snap["slo"]
        assert slo["target_ms"] == 50
        tenants = slo["tenants"]
        for c in range(self.N_CLIENTS):
            t = tenants[f"tenant{c}"]
            expected = self.PER_CLIENT + (2 if c == 0 else 0)
            assert t["count"] == expected, (c, t)
            assert 0 < t["p50_ms"] <= t["p95_ms"] <= t["p99_ms"], t
            assert set(t["breach_causes"]) <= set(_slo_mod.BREACH_CAUSES)
            assert sum(t["breach_causes"].values()) == t["breaches"], t
        # the two deadline_ms=1 queries breached with cause=deadline,
        # and the slow tenant's >50ms queries landed in the late causes
        t0_causes = tenants["tenant0"]["breach_causes"]
        assert t0_causes.get("deadline", 0) == 2, t0_causes
        assert tenants["tenant0"]["breaches"] >= 2
        assert tenants["tenant0"]["burn_ms"] > 0
