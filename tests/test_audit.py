"""Program-audit tests (analysis/program_audit.py + PV-FLUSH runtime
cross-check).

Four surfaces:

1. Rule unit contract — each seeded negative spec trips exactly its
   rule; clean integer programs pass; ``exact=False`` admits float
   math; spec-level ``# audit: allow(RULE)`` suppresses.
2. Jaxpr recursion — defects hidden inside ``lax.scan`` / ``lax.cond``
   bodies and nested ``jit`` (pjit) calls are still found.
3. Coverage contract — every REQUIRED_PROGRAMS entry has a registered
   spec, and the shipped program surface audits clean end to end (the
   same gate ``ci/audit.py`` runs).
4. PV-FLUSH vs runtime — the static warm-flush prediction equals the
   runtime ``pending.FLUSH_COUNT`` delta EXACTLY on the TPC-DS quartet
   with superstage on and off, and the prediction is invariant under
   pipeline parallelism (dispatch structure is a plan property, not a
   scheduling property).
"""
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import tpcds  # noqa: E402

from harness import with_tpu_session  # noqa: E402

from spark_rapids_tpu.analysis import predict_flushes
from spark_rapids_tpu.analysis import program_audit as PA
from spark_rapids_tpu.columnar import pending

QUARTET = ("q3", "q42", "q52", "q96")


def _spec(name, build, exact=True, budgets=None):
    return PA.AuditSpec(name, name, build, exact=exact, budgets=budgets)


def _i64(n=8):
    return jax.ShapeDtypeStruct((n,), np.int64)


# ---------------------------------------------------------------------------
# 1. rule unit contract
# ---------------------------------------------------------------------------

class TestRules:
    @pytest.mark.parametrize("rule", sorted(PA.ALL_RULES))
    def test_seeded_negative_trips_exactly_its_rule(self, rule):
        spec = PA.seeded_negative_specs()[rule]
        findings, _census = PA.audit_spec(spec)
        assert {f.rule for f in findings} == {rule}, findings
        assert all(spec.name in f.message for f in findings)

    def test_clean_integer_program_passes(self):
        def build():
            def f(x):
                return x * 2 + 1
            return f, (_i64(),), {}
        findings, census = PA.audit_spec(_spec("clean", build))
        assert findings == []
        assert census == {}

    def test_float_math_admitted_when_exact_false(self):
        def build():
            def f(x):
                return (x.astype(jnp.float32) * 0.5).astype(jnp.int64)
            return f, (_i64(),), {}
        findings, _ = PA.audit_spec(_spec("f32", build, exact=False))
        assert findings == []

    def test_budget_at_exact_count_passes(self):
        def build():
            def f(x, idx):
                return jnp.take(x, idx)
            return f, (_i64(), jax.ShapeDtypeStruct((4,), np.int32)), {}
        findings, census = PA.audit_spec(
            _spec("one_gather", build, budgets={"gather": 1}))
        assert findings == []
        assert census.get("gather") == 1

    def test_build_failure_is_loud_not_clean(self):
        def build():
            raise RuntimeError("provider broke")
        with pytest.raises(PA.AuditBuildError):
            PA.audit_spec(_spec("broken", build))


# ---------------------------------------------------------------------------
# 2. recursion into scan / cond / pjit sub-jaxprs
# ---------------------------------------------------------------------------

class TestRecursion:
    def test_float_inside_scan_body_found(self):
        def build():
            def f(x):
                def body(carry, t):
                    y = (t.astype(jnp.float32) * 2.0).astype(jnp.int64)
                    return carry + y, y
                total, _ = jax.lax.scan(body, jnp.int64(0), x)
                return total
            return f, (_i64(),), {}
        findings, _ = PA.audit_spec(_spec("scan_f32", build))
        assert any(f.rule == PA.AUD002 for f in findings)

    def test_float_inside_cond_branch_found(self):
        def build():
            def f(x):
                return jax.lax.cond(
                    x[0] > 0,
                    lambda v: (v.astype(jnp.float64) + 0.5)
                    .astype(jnp.int64),
                    lambda v: v,
                    x)
            return f, (_i64(),), {}
        findings, _ = PA.audit_spec(_spec("cond_f64", build))
        assert any(f.rule == PA.AUD002 for f in findings)

    def test_callback_inside_nested_jit_found(self):
        def build():
            @jax.jit
            def inner(x):
                return jax.pure_callback(
                    lambda v: np.asarray(v),
                    jax.ShapeDtypeStruct(x.shape, x.dtype), x)

            def f(x):
                return inner(x) + 1
            return f, (_i64(),), {}
        findings, _ = PA.audit_spec(_spec("pjit_cb", build))
        assert any(f.rule == PA.AUD001 for f in findings)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

class TestSuppressions:
    def test_spec_level_allow_suppresses(self):
        def build():
            def f(x):
                return (x.astype(jnp.float32) * 2.0).astype(jnp.int64)
            return f, (_i64(),), {}
        spec = PA.AuditSpec("sup", "sup", build)  # audit: allow(AUD002)
        assert PA.spec_allowed_rules(spec) == {PA.AUD002}
        findings, _ = PA.audit_spec(spec)
        assert findings == []

    def test_allow_does_not_leak_to_other_rules(self):
        def build():
            def f(x):
                return jax.pure_callback(
                    lambda v: np.asarray(v),
                    jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return f, (_i64(),), {}
        spec = PA.AuditSpec("sup2", "sup2", build)  # audit: allow(AUD002)
        findings, _ = PA.audit_spec(spec)
        assert any(f.rule == PA.AUD001 for f in findings)


# ---------------------------------------------------------------------------
# 3. coverage contract + the shipped surface audits clean
# ---------------------------------------------------------------------------

class TestCoverage:
    def test_every_required_program_has_a_spec(self):
        specs = PA.collect_specs()
        assert PA.coverage_gaps(specs) == []
        assert PA.REQUIRED_PROGRAMS <= {s.name for s in specs}

    def test_shipped_programs_audit_clean(self):
        report = PA.audit_all()
        assert report.ok, "\n".join(str(f) for f in report.findings)
        assert set(report.audited) >= PA.REQUIRED_PROGRAMS
        # the stats program is the one sanctioned float surface
        exact = {s.name: s.exact for s in PA.collect_specs()}
        assert exact["exchange_stats"] is False
        assert all(v for k, v in exact.items() if k != "exchange_stats")


# ---------------------------------------------------------------------------
# 4. PV-FLUSH prediction == runtime FLUSH_COUNT delta
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcds_audit") / "sf")
    tpcds.generate(d, scale=0.002, seed=11)
    return d


def _predicted_and_observed(tpcds_dir, query, conf):
    def fn(s):
        tpcds.register(s, tpcds_dir)
        sql = tpcds.QUERIES[query]
        phys = s._plan(s.sql(sql)._plan)
        pred = predict_flushes(phys, conf=s.conf)
        s.sql(sql).collect()               # warm (compile caches)
        f0 = pending.FLUSH_COUNT
        rows = s.sql(sql).collect()
        return pred.expected(len(rows)), pending.FLUSH_COUNT - f0
    return with_tpu_session(fn, conf)


class TestFlushPredictionMatchesRuntime:
    @pytest.mark.parametrize("superstage", [True, False])
    def test_q42_prediction_exact(self, tpcds_dir, superstage):
        pred, obs = _predicted_and_observed(
            tpcds_dir, "q42",
            {"spark.rapids.tpu.sql.superstage": superstage})
        assert pred == obs, (pred, obs)

    @pytest.mark.slow
    @pytest.mark.parametrize("query", QUARTET)
    @pytest.mark.parametrize("superstage", [True, False])
    def test_quartet_prediction_exact(self, tpcds_dir, query,
                                      superstage):
        pred, obs = _predicted_and_observed(
            tpcds_dir, query,
            {"spark.rapids.tpu.sql.superstage": superstage})
        assert pred == obs, (query, superstage, pred, obs)

    @pytest.mark.parametrize("superstage", [True, False])
    def test_prediction_invariant_under_parallelism(self, tpcds_dir,
                                                    superstage):
        def predict(par):
            def fn(s):
                tpcds.register(s, tpcds_dir)
                phys = s._plan(s.sql(tpcds.QUERIES["q3"])._plan)
                return predict_flushes(phys, conf=s.conf).warm
            return with_tpu_session(fn, {
                "spark.rapids.tpu.sql.superstage": superstage,
                "spark.rapids.tpu.exec.pipelineParallelism": par,
                "spark.rapids.tpu.exec.pipelinePrefetchDepth": par,
            })
        assert predict(1) == predict(4)
